// logdb: append-only log-structured KV engine with an ordered in-memory
// index. The native storage backend for cometbft_tpu (the role
// goleveldb/pebble — both native LSM engines — play for the reference's
// cometbft-db seam).
//
// Design:
//   * One data file: a sequence of CRC-framed records
//       [crc32(4) | klen(4) | vlen(4, 0xFFFFFFFF = tombstone) | key | value]
//     appended on every set/delete. A batch is ONE record with the
//     sentinel klen 0xFFFFFFFE framing its whole serialized payload, so
//     replay applies a batch entirely or not at all — a torn tail fails
//     the single CRC and truncates (the crash-atomicity the reference
//     gets from its LSM engines' WAL).
//   * The file is flock()ed exclusively on open: a second process gets
//     a clean failure instead of silently corrupting offsets.
//   * Index: std::map<key, (offset, vlen)> rebuilt by replaying the log
//     on open; ordered, so prefix iteration is a lower_bound walk.
//   * Compaction rewrites live records to <path>.compact and renames it
//     into place (crash-safe: rename is atomic).
//
// Exposed as a C ABI for the Python ctypes binding
// (cometbft_tpu/utils/logdb.py). No exceptions across the boundary.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int j = 0; j < 8; j++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* p, size_t n, uint32_t seed = 0) {
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

constexpr uint32_t kTombstone = 0xFFFFFFFFu;
constexpr uint32_t kBatchMark = 0xFFFFFFFEu;

struct Entry {
  uint64_t offset;  // file offset of the VALUE bytes
  uint32_t vlen;
};

struct DB {
  std::mutex mu;
  std::string path;
  int fd = -1;
  uint64_t end = 0;  // append position
  std::map<std::string, Entry> index;
  uint64_t dead = 0;  // bytes of overwritten/tombstoned records

  int replay();
  int append_record(const std::string& k, const uint8_t* v, uint32_t vl,
                    bool flush);
  void index_op(const std::string& key, uint64_t voff, uint32_t vlen);
};

int write_all(int fd, const uint8_t* p, size_t n) {
  while (n) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return 0;
}

void DB::index_op(const std::string& key, uint64_t voff, uint32_t vlen) {
  auto it = index.find(key);
  if (it != index.end())
    dead += 12 + key.size() + (it->second.vlen ? it->second.vlen : 0);
  if (vlen == kTombstone) {
    if (it != index.end()) index.erase(it);
    dead += 12 + key.size();
  } else {
    index[key] = Entry{voff, vlen};
  }
}

int DB::replay() {
  struct stat st;
  if (fstat(fd, &st) != 0) return -1;
  uint64_t size = static_cast<uint64_t>(st.st_size);
  const uint8_t* buf = nullptr;
  void* mapped = nullptr;
  if (size) {
    // mmap instead of a full-file heap buffer: O(page cache) replay
    // and no bad_alloc escaping the C ABI on multi-GB logs
    mapped = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (mapped == MAP_FAILED) return -1;
    buf = static_cast<const uint8_t*>(mapped);
  }
  uint64_t pos = 0;
  while (pos + 12 <= size) {
    uint32_t crc, klen, vlen;
    memcpy(&crc, &buf[pos], 4);
    memcpy(&klen, &buf[pos + 4], 4);
    memcpy(&vlen, &buf[pos + 8], 4);
    if (klen == kBatchMark) {
      // one whole batch framed by a single CRC: vlen = payload length
      if (vlen > (512u << 20) || pos + 12 + vlen > size) break;
      if (crc32(&buf[pos + 4], 8 + vlen) != crc) break;
      const uint8_t* p = &buf[pos + 12];
      uint64_t off = pos + 12, bp = 0;
      bool ok = true;
      uint32_t nsets, ndels;
      auto rd32 = [&](uint32_t* v) {
        if (bp + 4 > vlen) return false;
        memcpy(v, p + bp, 4);
        bp += 4;
        return true;
      };
      std::vector<std::tuple<std::string, uint64_t, uint32_t>> ops;
      if (!rd32(&nsets)) break;
      for (uint32_t i = 0; ok && i < nsets; i++) {
        uint32_t kl, vl;
        if (!rd32(&kl) || !rd32(&vl) ||
            bp + kl + static_cast<uint64_t>(vl) > vlen) { ok = false; break; }
        ops.emplace_back(std::string(reinterpret_cast<const char*>(p + bp), kl),
                         off + bp + kl, vl);
        bp += kl + static_cast<uint64_t>(vl);
      }
      if (ok && rd32(&ndels)) {
        for (uint32_t i = 0; ok && i < ndels; i++) {
          uint32_t kl;
          if (!rd32(&kl) || bp + kl > vlen) { ok = false; break; }
          ops.emplace_back(std::string(reinterpret_cast<const char*>(p + bp), kl),
                           0, kTombstone);
          bp += kl;
        }
      } else {
        ok = false;
      }
      if (!ok) break;  // malformed payload inside a valid CRC: stop
      for (auto& [key, voff, vl] : ops) index_op(key, voff, vl);
      pos += 12 + vlen;
      continue;
    }
    uint64_t body = static_cast<uint64_t>(klen) +
                    (vlen == kTombstone ? 0 : vlen);
    if (klen > (64u << 20) || (vlen != kTombstone && vlen > (256u << 20)) ||
        pos + 12 + body > size)
      break;  // torn/garbage tail
    uint32_t got = crc32(&buf[pos + 4], 8 + body);
    if (got != crc) break;  // torn write: truncate here
    std::string key(reinterpret_cast<const char*>(&buf[pos + 12]), klen);
    index_op(key, pos + 12 + klen, vlen);
    pos += 12 + body;
  }
  if (mapped) munmap(mapped, size);
  // truncate any torn tail so future appends start from a clean point
  if (pos != size) {
    if (ftruncate(fd, static_cast<off_t>(pos)) != 0) return -1;
  }
  end = pos;
  return 0;
}

int DB::append_record(const std::string& k, const uint8_t* v, uint32_t vl,
                      bool flush) {
  bool tomb = (v == nullptr);
  uint32_t klen = static_cast<uint32_t>(k.size());
  uint32_t vlen = tomb ? kTombstone : vl;
  uint64_t body = klen + (tomb ? 0 : vl);
  std::vector<uint8_t> rec(12 + body);
  memcpy(&rec[4], &klen, 4);
  memcpy(&rec[8], &vlen, 4);
  memcpy(&rec[12], k.data(), klen);
  if (!tomb && vl) memcpy(&rec[12 + klen], v, vl);
  uint32_t crc = crc32(&rec[4], 8 + body);
  memcpy(&rec[0], &crc, 4);
  if (write_all(fd, rec.data(), rec.size()) != 0) return -1;
  auto it = index.find(k);
  if (it != index.end())
    dead += 12 + klen + (it->second.vlen ? it->second.vlen : 0);
  if (tomb) {
    if (it != index.end()) index.erase(it);
    dead += 12 + klen;
  } else {
    index[k] = Entry{end + 12 + klen, vl};
  }
  end += rec.size();
  if (flush) {
    // data integrity relies on record CRCs; fdatasync on every write
    // would serialize the commit path, so flush batches only
#ifdef __APPLE__
    fsync(fd);
#else
    fdatasync(fd);
#endif
  }
  return 0;
}

struct Iter {
  std::vector<std::pair<std::string, std::string>> items;  // snapshot
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* logdb_open(const char* path) {
  DB* db = new DB();
  db->path = path;
  db->fd = ::open(path, O_RDWR | O_CREAT | O_APPEND, 0644);
  if (db->fd < 0) {
    delete db;
    return nullptr;
  }
  if (flock(db->fd, LOCK_EX | LOCK_NB) != 0) {
    // another process owns this log; silent double-writers would
    // desync offsets undetectably (reads are not CRC-verified)
    ::close(db->fd);
    delete db;
    return nullptr;
  }
  if (db->replay() != 0) {
    ::close(db->fd);
    delete db;
    return nullptr;
  }
  return db;
}

// 0 = found (out malloc'd), 1 = missing, -1 = io error
int logdb_get(void* h, const uint8_t* k, uint32_t kl, uint8_t** out,
              uint32_t* outl) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  auto it = db->index.find(std::string(reinterpret_cast<const char*>(k), kl));
  if (it == db->index.end()) return 1;
  uint32_t vl = it->second.vlen;
  uint8_t* buf = static_cast<uint8_t*>(malloc(vl ? vl : 1));
  if (vl) {
    ssize_t r = pread(db->fd, buf, vl, static_cast<off_t>(it->second.offset));
    if (r < 0 || static_cast<uint32_t>(r) != vl) {
      free(buf);
      return -1;
    }
  }
  *out = buf;
  *outl = vl;
  return 0;
}

int logdb_put(void* h, const uint8_t* k, uint32_t kl, const uint8_t* v,
              uint32_t vl) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return db->append_record(
      std::string(reinterpret_cast<const char*>(k), kl), v ? v : (const uint8_t*)"", vl,
      false);
}

int logdb_del(void* h, const uint8_t* k, uint32_t kl) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return db->append_record(
      std::string(reinterpret_cast<const char*>(k), kl), nullptr, 0, false);
}

// batch buffer: [nsets(4)] then per set [klen(4) vlen(4) key value],
// [ndels(4)] then per del [klen(4) key]. Appended as ONE CRC-framed
// record (sentinel klen kBatchMark) so a crash applies all or nothing.
int logdb_batch(void* h, const uint8_t* buf, uint64_t len) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  if (len > (512u << 20)) return -2;
  // validate + collect index ops relative to the payload start
  uint64_t pos = 0;
  auto rd32 = [&](uint32_t* v) -> bool {
    if (pos + 4 > len) return false;
    memcpy(v, buf + pos, 4);
    pos += 4;
    return true;
  };
  std::vector<std::tuple<std::string, uint64_t, uint32_t>> ops;
  uint32_t nsets;
  if (!rd32(&nsets)) return -2;
  for (uint32_t i = 0; i < nsets; i++) {
    uint32_t kl, vl;
    if (!rd32(&kl) || !rd32(&vl)) return -2;
    if (pos + kl + static_cast<uint64_t>(vl) > len) return -2;
    ops.emplace_back(std::string(reinterpret_cast<const char*>(buf + pos), kl),
                     pos + kl, vl);
    pos += kl + static_cast<uint64_t>(vl);
  }
  uint32_t ndels;
  if (!rd32(&ndels)) return -2;
  for (uint32_t i = 0; i < ndels; i++) {
    uint32_t kl;
    if (!rd32(&kl)) return -2;
    if (pos + kl > len) return -2;
    ops.emplace_back(std::string(reinterpret_cast<const char*>(buf + pos), kl),
                     0, kTombstone);
    pos += kl;
  }
  // frame: [crc | kBatchMark | len | payload]
  std::vector<uint8_t> hdr(12);
  uint32_t plen = static_cast<uint32_t>(len);
  memcpy(&hdr[4], &kBatchMark, 4);
  memcpy(&hdr[8], &plen, 4);
  uint32_t crc = crc32(&hdr[4], 8);
  crc = crc32(buf, len, crc) ;
  memcpy(&hdr[0], &crc, 4);
  if (write_all(db->fd, hdr.data(), hdr.size()) != 0) return -1;
  if (write_all(db->fd, buf, len) != 0) return -1;
  uint64_t payload_base = db->end + 12;
  for (auto& [key, rel, vl] : ops)
    db->index_op(key, vl == kTombstone ? 0 : payload_base + rel, vl);
  db->end += 12 + len;
#ifdef __APPLE__
  fsync(db->fd);
#else
  fdatasync(db->fd);
#endif
  return 0;
}

void* logdb_iter_new(void* h, const uint8_t* prefix, uint32_t pl) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  std::string pre(reinterpret_cast<const char*>(prefix), pl);
  Iter* it = new Iter();
  for (auto mi = db->index.lower_bound(pre); mi != db->index.end(); ++mi) {
    if (mi->first.compare(0, pre.size(), pre) != 0) break;
    std::string val;
    val.resize(mi->second.vlen);
    if (mi->second.vlen) {
      ssize_t r = pread(db->fd, &val[0], mi->second.vlen,
                        static_cast<off_t>(mi->second.offset));
      if (r < 0 || static_cast<uint32_t>(r) != mi->second.vlen) {
        delete it;
        return nullptr;
      }
    }
    it->items.emplace_back(mi->first, std::move(val));
  }
  return it;
}

int logdb_iter_next(void* hi, const uint8_t** k, uint32_t* kl,
                    const uint8_t** v, uint32_t* vl) {
  Iter* it = static_cast<Iter*>(hi);
  if (it->pos >= it->items.size()) return 1;
  auto& kv = it->items[it->pos++];
  *k = reinterpret_cast<const uint8_t*>(kv.first.data());
  *kl = static_cast<uint32_t>(kv.first.size());
  *v = reinterpret_cast<const uint8_t*>(kv.second.data());
  *vl = static_cast<uint32_t>(kv.second.size());
  return 0;
}

void logdb_iter_free(void* hi) { delete static_cast<Iter*>(hi); }

// rewrite live records; atomic rename. Returns reclaimed bytes or <0.
int64_t logdb_compact(void* h) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  std::string tmp = db->path + ".compact";
  int nfd = ::open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC | O_APPEND, 0644);
  if (nfd < 0) return -1;
  // the exclusive lock must survive the fd swap below, or a second
  // process could open the db after compaction and double-write
  if (flock(nfd, LOCK_EX | LOCK_NB) != 0) {
    ::close(nfd);
    unlink(tmp.c_str());
    return -1;
  }
  uint64_t old_end = db->end;
  std::map<std::string, Entry> nindex;
  uint64_t nend = 0;
  for (auto& [key, e] : db->index) {
    std::vector<uint8_t> rec(12 + key.size() + e.vlen);
    uint32_t klen = static_cast<uint32_t>(key.size());
    memcpy(&rec[4], &klen, 4);
    memcpy(&rec[8], &e.vlen, 4);
    memcpy(&rec[12], key.data(), klen);
    if (e.vlen) {
      ssize_t r = pread(db->fd, &rec[12 + klen], e.vlen,
                        static_cast<off_t>(e.offset));
      if (r < 0 || static_cast<uint32_t>(r) != e.vlen) {
        ::close(nfd);
        unlink(tmp.c_str());
        return -1;
      }
    }
    uint32_t crc = crc32(&rec[4], rec.size() - 4);
    memcpy(&rec[0], &crc, 4);
    if (write_all(nfd, rec.data(), rec.size()) != 0) {
      ::close(nfd);
      unlink(tmp.c_str());
      return -1;
    }
    nindex[key] = Entry{nend + 12 + klen, e.vlen};
    nend += rec.size();
  }
  fsync(nfd);
  if (rename(tmp.c_str(), db->path.c_str()) != 0) {
    ::close(nfd);
    unlink(tmp.c_str());
    return -1;
  }
  // persist the rename itself before dropping the old fd
  std::string dir = db->path;
  size_t slash = dir.find_last_of('/');
  dir = (slash == std::string::npos) ? "." : dir.substr(0, slash);
  int dfd = ::open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd >= 0) {
    fsync(dfd);
    ::close(dfd);
  }
  ::close(db->fd);
  db->fd = nfd;
  db->index = std::move(nindex);
  db->end = nend;
  db->dead = 0;
  return static_cast<int64_t>(old_end - nend);
}

uint64_t logdb_count(void* h) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return db->index.size();
}

uint64_t logdb_dead_bytes(void* h) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return db->dead;
}

void logdb_flush(void* h) {
  DB* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
#ifdef __APPLE__
  fsync(db->fd);
#else
  fdatasync(db->fd);
#endif
}

void logdb_close(void* h) {
  DB* db = static_cast<DB*>(h);
  {
    std::lock_guard<std::mutex> g(db->mu);
#ifdef __APPLE__
    fsync(db->fd);
#else
    fdatasync(db->fd);
#endif
    ::close(db->fd);
  }
  delete db;
}

void logdb_free(void* p) { free(p); }

}  // extern "C"
