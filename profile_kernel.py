"""Component-level timing of the ed25519 verify kernel on TPU.

Times each stage separately (double chain, cached adds, table build,
select_n lookups, SHA-512, decompress, scalar ops) with the same
chained-dispatch methodology as bench.py so tunnel latency cancels.
"""

import json
import os
import sys
import time

import numpy as np

N = int(os.environ.get("PROF_N", "8192"))


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)

    from cometbft_tpu.ops import curve25519 as curve
    from cometbft_tpu.ops import ed25519 as ed
    from cometbft_tpu.ops import fe25519 as fe
    from cometbft_tpu.ops import sc25519 as sc
    from cometbft_tpu.ops import sha512

    rng = np.random.default_rng(0)

    def rand_fe():
        return jnp.asarray(
            rng.integers(0, 1 << 13, size=(fe.NLIMBS, N), dtype=np.int32)
        )

    def rand_pt():
        # not actually on curve; arithmetic cost is identical
        return (rand_fe(), rand_fe(), rand_fe(), rand_fe())

    def timeit(name, fn, *args, inner=1):
        """Compile fn, then time CHAIN dependent dispatches."""
        comp = jax.jit(fn).lower(*args).compile()
        out = comp(*args)
        jax.block_until_ready(out)
        # measure round-trip with a tiny noop
        tiny = jax.device_put(jnp.zeros((1,), jnp.int32))
        noop = jax.jit(lambda x: x + 1).lower(tiny).compile()
        np.asarray(noop(tiny))
        rts = []
        for _ in range(3):
            t0 = time.time()
            np.asarray(noop(tiny))
            rts.append(time.time() - t0)
        rt = min(rts)
        CHAIN = 6
        best = 1e9
        for _ in range(2):
            a0 = args
            t0 = time.time()
            for _k in range(CHAIN):
                out = comp(*a0)
                if isinstance(out, tuple):
                    a0 = (out[0],) + tuple(args[1:])
                else:
                    a0 = (out,) + tuple(args[1:])
            if isinstance(out, tuple):
                np.asarray(out[0])
            else:
                np.asarray(out)
            dt = (time.time() - t0 - rt) / CHAIN
            best = min(best, dt)
        per_item = best / inner
        print(
            json.dumps(
                {
                    "stage": name,
                    "ms": round(best * 1e3, 2),
                    "ms_per_unit": round(per_item * 1e3, 3),
                    "inner": inner,
                }
            ),
            flush=True,
        )
        return best

    # --- stages -----------------------------------------------------

    q = rand_pt()

    def chain_double(x, y, z, t):
        p = tuple(fe.unstack(c) for c in (x, y, z, t))
        for _ in range(16):
            p = curve.double(p)
        return fe.stack(p[0])

    timeit("double x16", chain_double, *q, inner=16)

    cq_arr = tuple(rand_fe() for _ in range(4))

    def chain_add(x, y, z, t):
        p = tuple(fe.unstack(c) for c in (x, y, z, t))
        cq = tuple(fe.unstack(c) for c in cq_arr)
        for _ in range(16):
            p = curve.add_cached(p, cq)
        return fe.stack(p[0])

    timeit("add_cached x16", chain_add, *q, inner=16)

    def chain_mul(a, b):
        x, y = fe.unstack(a), fe.unstack(b)
        for _ in range(16):
            x = fe.mul(x, y)
        return fe.stack(x)

    timeit("fe.mul x16", chain_mul, rand_fe(), rand_fe(), inner=16)

    def chain_sqr(a, b):
        x = fe.unstack(a)
        for _ in range(16):
            x = fe.square(x)
        return fe.stack(x)

    timeit("fe.square x16", chain_sqr, rand_fe(), rand_fe(), inner=16)

    # table build: 15 adds + to_cached
    def table_build(x, y, z, t):
        A = tuple(fe.unstack(c) for c in (x, y, z, t))
        ext = curve.identity(x.shape[1:])
        outs = [curve.to_cached(ext)]
        for _ in range(15):
            ext = curve.add(ext, A)
            outs.append(curve.to_cached(ext))
        return fe.stack(outs[-1][0])

    timeit("A-table build (15 adds)", table_build, *q)

    # select_n lookup: 16-way over a (16, 20, N) per component
    tbl = jnp.asarray(
        rng.integers(0, 1 << 13, size=(16, fe.NLIMBS, N), dtype=np.int32)
    )
    ds = jnp.asarray(rng.integers(0, 16, size=(N,), dtype=np.int32))

    def chain_sel(d0):
        acc = jnp.zeros((fe.NLIMBS, N), jnp.int32)
        for k in range(16):
            sel = jnp.broadcast_to(
                ((d0 + k) % 16)[None], (fe.NLIMBS, N)
            )
            acc = acc + lax.select_n(sel, *[tbl[i] for i in range(16)])
        return acc[0] + d0

    timeit("select_n 16way x16", chain_sel, ds, inner=16)

    # SHA-512 over 175+64 = 239-byte inputs
    hin = jnp.asarray(
        rng.integers(0, 256, size=(239, N), dtype=np.uint8)
    )
    lens = jnp.full((N,), 184, jnp.int32)

    def do_sha(h):
        return sha512.sha512(h, lens, 239)

    comp = jax.jit(do_sha).lower(hin).compile()
    out = np.asarray(comp(hin))
    t0 = time.time()
    for _ in range(4):
        out = comp(hin)
    np.asarray(out)
    print(
        json.dumps(
            {"stage": "sha512 (239B)", "ms": round((time.time() - t0) / 4 * 1e3, 2)}
        ),
        flush=True,
    )

    # decompress (includes pow2523 exponentiation: ~254 squarings)
    pk = jnp.asarray(rng.integers(0, 256, size=(32, N), dtype=np.uint8))

    def do_dec(p):
        A, ok = curve.decompress(p)
        return fe.stack(A[0])

    comp = jax.jit(do_dec).lower(pk).compile()
    out = np.asarray(comp(pk))
    t0 = time.time()
    for _ in range(4):
        out = comp(pk)
    np.asarray(out)
    print(
        json.dumps(
            {"stage": "decompress x1", "ms": round((time.time() - t0) / 4 * 1e3, 2)}
        ),
        flush=True,
    )



if __name__ == "__main__":
    main()
