#!/usr/bin/env bash
# Seeded chaos smoke: one partition/heal + crash/restart schedule with
# all three BFT invariant checkers, then the SAME schedule with an
# injected byzantine commit corruption that the agreement checker must
# flag (exit inverts for the second run — a missed detection fails).
#
# Tier-1 exercises the same paths via tests/test_chaos.py; this script
# is the standalone entry (CI cron, local bisecting):
#
#   CHAOS_SEED=99 tools/chaos_smoke.sh
#
# Replay a failing run: feed the printed seed back via CHAOS_SEED and
# keep the schedule JSON (see docs/CHAOS.md).
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${CHAOS_SEED:-1337}"
TRACE_DIR="$(mktemp -d -t chaos_smoke_trace.XXXXXX)"
trap 'rm -rf "$TRACE_DIR"' EXIT

echo "== chaos smoke: invariants + span budgets + sanitizer must hold (seed=$SEED) =="
# --budget evaluates tools/span_budgets.toml over the run's rings and
# prints the verdict table in the report (docs/OBS.md); a breach exits 2.
# This leg is ALSO the sanitizer-enabled zero-findings assert: every
# chaos node runs the runtime concurrency sanitizer (docs/LINT.md
# "Runtime sanitizer"), and any lock-order cycle or foreign-thread
# touch of a loop-affine object during the run is an invariant-style
# violation (exit 1)
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --trace-dump "$TRACE_DIR" --budget

echo "== chaos smoke: per-node span summary + budget table (docs/TRACE.md) =="
# note: paths BEFORE --budget (its optional FILE value would swallow
# a trailing path)
python -m cometbft_tpu.trace summarize "$TRACE_DIR" --budget

echo "== chaos smoke: per-height commit-latency attribution (docs/TRACE.md) =="
# cross-node causal timeline over the invariant run's rings: every
# committed height must carry a complete attribution chain (proposal
# send on the proposer correlated to arrivals on all committing
# peers, both quorum legs measured) — --strict exits 3 on a gap
python -m cometbft_tpu.trace timeline "$TRACE_DIR" --strict

echo "== chaos smoke: forced loop stall must be flight-recorded =="
# one seeded stall scenario: the nemesis blocks the loop for 1.2s at
# height 2; the obs watchdog's monitor thread must snapshot the
# offending chaos_stall frame mid-flight (exit 1 on a miss)
cat > "$TRACE_DIR/stall_schedule.json" <<'EOF'
[
  {"action": "stall", "at_height": 2, "duration_s": 1.2},
  {"action": "crash", "at_height": 3, "node": 1},
  {"action": "restart", "after_s": 0.5, "node": 1}
]
EOF
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --schedule "$TRACE_DIR/stall_schedule.json" --expect-stall \
    --trace-dump "$TRACE_DIR/stall"

echo "== chaos smoke: seeded lock inversion must be DETECTED =="
# checker validation (same discipline as the byzantine leg): a
# deliberate ABBA ordering + a foreign-thread affinity touch are
# injected at height 2; the sanitizer must report BOTH,
# deterministically from this seed line (exit 1 on a miss)
cat > "$TRACE_DIR/lockinv_schedule.json" <<'EOF'
[
  {"action": "lock_inversion", "at_height": 2},
  {"action": "crash", "at_height": 3, "node": 1},
  {"action": "restart", "after_s": 0.5, "node": 1}
]
EOF
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --schedule "$TRACE_DIR/lockinv_schedule.json" --expect-lock-inversion \
    --trace-dump "$TRACE_DIR/lockinv"

echo "== chaos smoke: planted quadratic site must be FLAGGED by the scaling probe =="
# complexity-plane checker validation (docs/LINT.md "Complexity
# rules"): the nemesis runs the committee-scaling probe mid-schedule
# with a deliberate O(n^2) plant; the probe must fit its exponent
# over budget (exit 1 on a miss), while the real fixed sites must
# stay under theirs (an un-injected breach is a violation)
cat > "$TRACE_DIR/scaling_schedule.json" <<'EOF'
[
  {"action": "scaling_probe", "at_height": 2, "inject_quadratic": true},
  {"action": "crash", "at_height": 3, "node": 1},
  {"action": "restart", "after_s": 0.5, "node": 1}
]
EOF
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --schedule "$TRACE_DIR/scaling_schedule.json" --expect-scaling-violation \
    --trace-dump "$TRACE_DIR/scaling"

echo "== chaos smoke: byzantine corruption must be DETECTED =="
# --trace-dump keeps the EXPECTED violation's auto-dump inside the
# trap-cleaned dir instead of leaking a /tmp/chaos_trace_* per run
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" --byzantine 2 \
    --trace-dump "$TRACE_DIR/byzantine"

echo "== chaos smoke: fast-path slice (group commit + vote batch + pipelined finalize), budget-gated =="
# the live-consensus fast path (docs/PERF.md) under faults: every
# node runs WAL group commit + in-round vote micro-batching +
# pipelined finalize beneath a 2ms slow-disk fsync model (so crashes
# and torn tails land inside group windows), gated on the SAME
# invariants + span budgets as the plain matrix — fault-clean, not
# just fast
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos matrix --seed "$SEED" \
    --count 3 --fastpath --budget --out "$TRACE_DIR/fastpath"

echo "== chaos smoke: fast-path waterfalls must stay complete + budget-clean =="
# the partition/heal + crash/restart schedule again WITH the fast
# path on: the changed finalize span shape (docs/TRACE.md) must not
# break per-height attribution — every committed height still needs
# a complete proposal->parts->quorum->finalize chain (--strict exits
# 3 on a gap) and the span budgets still hold (exit 2 on breach)
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" --fastpath \
    --trace-dump "$TRACE_DIR/fastpath_run" --budget
python -m cometbft_tpu.trace timeline "$TRACE_DIR/fastpath_run" --strict

echo "== chaos smoke: native finalize lane under faults (fastpath matrix, strict waterfalls) =="
# the native finalize lane (ISSUE 20, docs/PERF.md "Native finalize
# lane"): the fastpath matrix again with the lane explicitly
# exercised — the extension is resolved UP FRONT (off the schedules;
# the prewarm discipline), then every fastpath node finalizes through
# one GIL-releasing finalize_pass on the offloaded thread hop. The
# changed span shape (consensus.finalize.hash_persist riding inside
# the pipelined finalize, docs/TRACE.md) must keep per-height commit
# attribution complete on EVERY scenario (--strict exits 3 on a gap)
# and the span budgets clean (exit 2). On a no-g++ box the loader
# degrades to the byte-identical portable twin and the same gates
# still hold — that is the lane's contract, not a skip.
if JAX_PLATFORMS=cpu python -c 'from cometbft_tpu.state import native_finalize as nf; raise SystemExit(0 if nf.module() is not None else 1)'; then
    echo "   native finalize extension: built + loaded"
else
    echo "   native finalize extension: UNAVAILABLE (portable twin carries the slice)"
fi
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos matrix --seed "$SEED" \
    --count 3 --fastpath --budget --trace-dump "$TRACE_DIR/native_lane"
for d in "$TRACE_DIR/native_lane"/m*-*; do
    python -m cometbft_tpu.trace timeline "$d" --strict
done

echo "== chaos smoke: 5-scenario factory matrix, budget-gated =="
# seeded workload x network x lifecycle matrix (docs/CHAOS.md
# "Scenario factory"): any 5-window covers crash_wave,
# statesync_join, wal_torn_tail, adaptive_catchup and
# crash_restart+valset_churn; every scenario must be invariant-clean
# (exit 1) and budget-clean (exit 2), each replayable byte-for-byte
# via the printed "SCENARIO ... --only I" seed line
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos matrix --seed "$SEED" \
    --count 5 --budget --out "$TRACE_DIR/matrix"

echo "== chaos smoke: 200-session light serving storm against a live ChaosNet node =="
# the light-client serving plane (docs/PERF.md): after the fault
# schedule settles, 200 seeded light sessions storm the most advanced
# node through the shared verified-header cache + coalesced verify —
# every served block hash-asserted against the node's store, the
# light.serve.request spans budget-gated (exit 2 on breach), and the
# per-height commit waterfalls must stay complete under the storm
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" --light-storm 200 \
    --trace-dump "$TRACE_DIR/light_storm" --budget
python -m cometbft_tpu.trace timeline "$TRACE_DIR/light_storm" --strict

echo "== chaos smoke: 150-subscriber websocket storm against a live node's fan-out plane =="
# the outbound fan-out plane (ISSUE 15, docs/PERF.md): after the fault
# schedule settles, 150 real websocket subscribers storm the most
# advanced node — every subscriber must receive consecutive NewBlock
# events store-verified against the node, ZERO frames shed, and the
# hub must pay ~one JSON serialization per event (not per subscriber);
# fanout.deliver + fanout.index.flush spans budget-gated (exit 2)
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --subscriber-storm 150 --trace-dump "$TRACE_DIR/sub_storm" --budget
python -m cometbft_tpu.trace timeline "$TRACE_DIR/sub_storm" --strict

echo "== chaos smoke: 3-replica serving fleet — replica_kill mid-stream, lossless failover =="
# the serving fleet (ISSUE 19, docs/FLEET.md): three follower
# replicas tail the live net behind the SessionRouter while routed
# subscriber sessions stream commits; the schedule kills one replica
# mid-stream and the run asserts lossless failover (every stranded
# session resumed elsewhere with ZERO lost commits, height-keyed
# replay from the store) + lag-shed isolation (only the victim's
# clients move); fleet.route / fleet.failover spans budget-gated
# (exit 2 on breach) and the commit waterfalls must stay complete
cat > "$TRACE_DIR/fleet_schedule.json" <<'EOF'
[
  {"action": "replica_kill", "at_height": 3, "replica": 0},
  {"action": "crash", "at_height": 4, "node": 1},
  {"action": "restart", "after_s": 0.5, "node": 1}
]
EOF
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --schedule "$TRACE_DIR/fleet_schedule.json" --fleet 3 \
    --trace-dump "$TRACE_DIR/fleet" --budget
python -m cometbft_tpu.trace timeline "$TRACE_DIR/fleet" --strict

echo "== chaos smoke: verify storm — light + catch-up + live through ONE scheduler =="
# the unified verify scheduler (docs/PERF.md "Unified verify
# scheduler"): mid-schedule, a light-session storm and a
# blocksync-style catch-up storm hammer the SAME process-wide
# scheduler the net's live consensus verifies on. Verdict parity is
# asserted on every ticket (bad signatures included), the live
# class's p95 submit->resolve wall is gated on the
# crypto.sched.dispatch budget, and the catch-up lane must keep
# completing (aging promotion) — starvation, a budget breach, or a
# diverged verdict exits 1; span budgets gate the run like every leg
cat > "$TRACE_DIR/verify_storm_schedule.json" <<'EOF'
[
  {"action": "verify_storm", "at_height": 2},
  {"action": "crash", "at_height": 3, "node": 1},
  {"action": "restart", "after_s": 0.5, "node": 1}
]
EOF
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --schedule "$TRACE_DIR/verify_storm_schedule.json" \
    --trace-dump "$TRACE_DIR/verify_storm" --budget

echo "== chaos smoke: storage lifecycle plane under faults (crash mid-prune + snapshot during prune) =="
# the storage lifecycle plane (ISSUE 17, docs/STORAGE.md): the
# schedule crashes a node between bounded prune batches and restarts
# it (resume must be idempotent: base monotone, retained window fully
# readable, below-base gone), then races a statesync snapshot serve
# against a live prune pass (the serve floor must pin the served
# height). run_schedule turns the lifecycle knobs on for every node
# when these actions are scheduled; budget-gated like every leg
# (storage.prune / storage.snapshot budgets in tools/span_budgets.toml)
cat > "$TRACE_DIR/lifecycle_schedule.json" <<'EOF'
[
  {"action": "crash_mid_prune", "at_height": 3, "node": 1},
  {"action": "snapshot_during_prune", "at_height": 5, "node": 2}
]
EOF
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --schedule "$TRACE_DIR/lifecycle_schedule.json" \
    --trace-dump "$TRACE_DIR/lifecycle" --budget
python -m cometbft_tpu.trace timeline "$TRACE_DIR/lifecycle" --strict

echo "== chaos smoke: compressed-time storage soak slice (bounded disk + marker consistency) =="
# the 10k-height soak's CI-sized slice (docs/STORAGE.md "Soak"): one
# node, synthetic commit schedule, retention reconciled every 50
# heights — disk/RSS must plateau after warmup, prune markers
# (blocks base, idx:base, WAL group files) must stay consistent,
# below-base RPC must answer the structured pruned error, and a
# restart must replay only the retained tail (exit 1 on any
# violation; the full 10k soak is the slow-marked tier-2 run)
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos soak --seed "$SEED" \
    --heights 600 --step 50

echo "== chaos smoke: un-pinned partition x statesync_join x churn + reconnect span budget =="
# the compound the matrix previously pinned out (ISSUE 12): a
# partitioned net churns its valset, heals, and a fresh node joins by
# statesync mid-load — gated on the invariants, the span budgets
# (p2p.reconnect convergence included; exit 2 on breach) and, below,
# strict per-height commit attribution over the run's rings
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos matrix --seed "$SEED" \
    --only 11 --budget --trace-dump "$TRACE_DIR/join_partition"
python -m cometbft_tpu.trace timeline "$TRACE_DIR/join_partition"/m*-11 --strict
