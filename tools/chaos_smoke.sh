#!/usr/bin/env bash
# Seeded chaos smoke: one partition/heal + crash/restart schedule with
# all three BFT invariant checkers, then the SAME schedule with an
# injected byzantine commit corruption that the agreement checker must
# flag (exit inverts for the second run — a missed detection fails).
#
# Tier-1 exercises the same paths via tests/test_chaos.py; this script
# is the standalone entry (CI cron, local bisecting):
#
#   CHAOS_SEED=99 tools/chaos_smoke.sh
#
# Replay a failing run: feed the printed seed back via CHAOS_SEED and
# keep the schedule JSON (see docs/CHAOS.md).
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${CHAOS_SEED:-1337}"

echo "== chaos smoke: invariants must hold (seed=$SEED) =="
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED"

echo "== chaos smoke: byzantine corruption must be DETECTED =="
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" --byzantine 2
