#!/usr/bin/env bash
# Seeded chaos smoke: one partition/heal + crash/restart schedule with
# all three BFT invariant checkers, then the SAME schedule with an
# injected byzantine commit corruption that the agreement checker must
# flag (exit inverts for the second run — a missed detection fails).
#
# Tier-1 exercises the same paths via tests/test_chaos.py; this script
# is the standalone entry (CI cron, local bisecting):
#
#   CHAOS_SEED=99 tools/chaos_smoke.sh
#
# Replay a failing run: feed the printed seed back via CHAOS_SEED and
# keep the schedule JSON (see docs/CHAOS.md).
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${CHAOS_SEED:-1337}"
TRACE_DIR="$(mktemp -d -t chaos_smoke_trace.XXXXXX)"
trap 'rm -rf "$TRACE_DIR"' EXIT

echo "== chaos smoke: invariants must hold (seed=$SEED) =="
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" \
    --trace-dump "$TRACE_DIR"

echo "== chaos smoke: per-node span summary (docs/TRACE.md) =="
python -m cometbft_tpu.trace summarize "$TRACE_DIR"

echo "== chaos smoke: byzantine corruption must be DETECTED =="
# --trace-dump keeps the EXPECTED violation's auto-dump inside the
# trap-cleaned dir instead of leaking a /tmp/chaos_trace_* per run
JAX_PLATFORMS=cpu python -m cometbft_tpu.chaos --seed "$SEED" --byzantine 2 \
    --trace-dump "$TRACE_DIR/byzantine"
