#!/usr/bin/env bash
# bftlint entry point: syntax gate + static analysis.
#
# Runs from any cwd; invoked by tests/test_bftlint.py so it executes
# under the existing tier-1 verify command with no extra CI plumbing.
#
#   1. python -m compileall  — every file must at least parse/compile
#   2. python -m cometbft_tpu.analysis — async-safety + JAX hot-path
#      rules against the checked-in baseline (tools/bftlint_baseline.json)
#
# Regenerate the baseline after deliberately accepting a violation:
#   python -m cometbft_tpu.analysis --update-baseline
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q cometbft_tpu tests
# --fail-on-stale: a shrinking baseline must be ratcheted, never rot;
# --timings: the interprocedural pass's cost stays visible per rule
python -m cometbft_tpu.analysis cometbft_tpu --fail-on-stale --timings
