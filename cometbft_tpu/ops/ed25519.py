"""Batched ed25519 signature verification on TPU (the north-star kernel).

Replaces the reference's batch-verification seam — curve25519-voi's
``BatchVerifier`` created by ``crypto/batch/batch.go:10`` and consumed by
``types/validation.go:261 verifyCommitBatch`` — with one XLA program that
verifies N signatures in parallel lanes:

    per lane:  h  = SHA-512(R || A || M)  mod L          (on device)
               ok = [8]([S]B - [h]A - R) == identity     (ZIP-215, cofactored)

The double-scalar multiplication [S]B + [L-h]A runs as a shared 4-bit
windowed Straus ladder (64 windows x 4 doublings, one cached add from a
per-lane [d]A table and one affine-cached add from a host-precomputed
[d]B table per window, branch-free 16-way point selects), vectorized
over the batch on the 8x128 VPU lanes. All point/field math is int32
limb arithmetic (see fe25519).

Unlike the reference's random-linear-combination batch verify (which
rejects the whole batch on one bad signature and needs a CPU fallback
pass), every lane here returns its own verdict — a failed commit
verification can point at the exact bad vote with no re-verification.

The cofactored equation with per-lane verdicts is exactly ZIP-215, so
results match curve25519-voi vote-by-vote (reference
types/validation.go:261-320 semantics, including its all-or-nothing
fallback behavior, can be reproduced by AND-reducing the lane mask).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import curve25519 as curve
from . import fe25519 as fe
from . import sc25519 as sc
from . import sha512

# message capacity buckets: hash input is 64 + cap bytes; choosing
# cap = 128k - 64 - 17 makes the padded hash input exactly k blocks.
MSG_CAPS = (47, 175, 431, 943)


def bucket_cap(max_len: int) -> int:
    for c in MSG_CAPS:
        if max_len <= c:
            return c
    raise ValueError(f"message too long for verify kernel: {max_len}")


# straus-ladder window-loop unroll factor (bench-tunable; see _straus)
import os as _os

LADDER_UNROLL = int(_os.environ.get("GRAFT_LADDER_UNROLL", "1"))


_B_TABLE = None


def _b_table():
    global _B_TABLE
    if _B_TABLE is None:
        _B_TABLE = curve.base_window_table()  # (16, 3, 20) host const
    return _B_TABLE


def _straus(ds, dh, A, shape):
    """[s]B + [hneg]A over batch lanes (tuple-of-limbs field elements).

    4-bit windowed joint ladder: 64 windows x (4 doublings) — the first
    group acts on the identity — plus per window one add from the
    per-lane A table and one affine-cached add from the shared
    host-precomputed B table (7M). ~27% fewer field multiplies than the
    bitwise ladder (253 x (double + 9M add)), and the window tables'
    d=0 entries are the identity in cached form so the adds stay
    branch-free and complete.

    The A table is built ON DEVICE from the extended point ``A`` (15
    sequential curve.add's, cached-projective entries). A round-3
    experiment replaced it with host-precomputed (16, 3, 20, N) tables
    to shrink the HLO for the XLA CPU backend: the gather lookup form
    ran ~4x slower on TPU (breaks tuple-of-limbs fusion), the
    select-forest lookup form compiled for >26 min on the TPU backend,
    and NEITHER made the CPU-backend compile finish (>60 min on the
    1-core box in every variant) — so the on-device build stays
    (docs/PERF.md "CPU-backend compile pathology").

    ds / dh: (64, N) int32 window digits, LSB-first."""
    # backend precedence: GRAFT_PALLAS=1/0 forces pallas/XLA; unset =
    # pallas by default on accelerator backends at bulk widths only
    # (>= pallas_ladder.min_lanes(), the r5-measured win region — the
    # interpreter stands in off-TPU), else compact on the CPU backend,
    # else the tuple-form XLA ladder. Every branch condition here is
    # part of _ladder_backend_key so a mid-process flip retraces (the
    # width itself re-keys via the per-shape jit trace).
    if len(shape) == 1 and shape[0] % 128 == 0:
        from .pallas_ladder import pallas_enabled, straus_pallas

        if pallas_enabled(shape[0]):
            res = straus_pallas(ds, dh, A, shape)
            if res is not None:
                return res
            # no VMEM-safe blocking exists for this width (e.g. a
            # large prime sublane count like r=513 under the default
            # cap): fall through to the compact/XLA ladder, as the
            # straus_pallas docstring promises (ADVICE r5 medium)
    if fe.compact_mode():
        return _straus_compact(ds, dh, A, shape)
    ident = curve.identity(shape)

    # per-lane A table: cached([d]A) for d in 0..15 — kept as a list of
    # 16 tuple-form points; selection is per-limb select_n over (N,)
    # vectors (no stacked gather, no broadcasts)
    ext = ident
    a_cached = [curve.to_cached(ident)]
    for _ in range(15):
        ext = curve.add(ext, A)
        a_cached.append(curve.to_cached(ext))

    # shared B table: (16, 3, 20) host constants; selected per limb as
    # scalar-broadcast cases (constant-folded by XLA)
    bt = _b_table()  # numpy (16, 3, 20) int32

    def body(i, q):
        j = 63 - i
        d_s = lax.dynamic_index_in_dim(ds, j, 0, keepdims=False)
        d_h = lax.dynamic_index_in_dim(dh, j, 0, keepdims=False)
        # only the last double's T is consumed (by the A window add);
        # the window-final add's T is never read (next op is a double)
        q = curve.double(
            curve.double(
                curve.double(curve.double(q, need_t=False), need_t=False),
                need_t=False,
            )
        )
        addend_a = tuple(
            tuple(
                lax.select_n(
                    d_h, *[a_cached[d][k][lj] for d in range(16)]
                )
                for lj in range(fe.NLIMBS)
            )
            for k in range(4)
        )
        q = curve.add_cached(q, addend_a)
        # shared B term: scalar-broadcast cases constant-folded by XLA
        addend_b = tuple(
            tuple(
                lax.select_n(
                    d_s,
                    *[
                        jnp.broadcast_to(
                            jnp.int32(int(bt[d, k, lj])), shape
                        )
                        for d in range(16)
                    ],
                )
                for lj in range(fe.NLIMBS)
            )
            for k in range(3)
        )
        return curve.add_affine_cached(q, addend_b, need_t=False)

    # T-less carry: the loop output feeds add_projective (no T input).
    # Unrolling trades HLO size for scheduling freedom across window
    # iterations (the kernel is issue-bound, not multiply-bound —
    # docs/PERF.md); the default is measured on v5e via bench.py.
    return lax.fori_loop(
        0, 64, body, ident[:3] + (None,), unroll=LADDER_UNROLL
    )


def _stack_pt(p):
    """tuple-form point -> stacked (ncomp, 20, N...) int32 array."""
    return jnp.stack([fe.stack(c) for c in p])


def _unstack_pt(arr):
    """stacked (ncomp, 20, N...) -> tuple-form point."""
    return tuple(
        tuple(arr[k, i] for i in range(fe.NLIMBS))
        for k in range(arr.shape[0])
    )


def _straus_compact(ds, dh, A, shape):
    """Compact-mode ladder for the XLA CPU backend: identical window
    schedule to _straus, but the per-lane A table is built by a
    15-step lax.scan into ONE stacked (16, 4, 20, N) array and window
    entries are fetched with take_along_axis instead of 16-way
    select_n trees. On TPU the gather form measured ~4x slower (it
    breaks tuple-of-limbs fusion — docs/PERF.md round-3 record), but
    here the target is compile-tractability: together with the rolled
    field ops it takes the CPU backend's compile from >80 min to
    seconds, which is what lets the virtual-mesh dryrun and the CPU
    test lane execute the REAL kernel graph (VERDICT r3 #1/#4)."""
    ident = curve.identity(shape)

    def build_step(ext_st, _):
        ext = _unstack_pt(ext_st)
        nxt = curve.add(ext, A)
        return _stack_pt(nxt), _stack_pt(curve.to_cached(nxt))

    _, entries = lax.scan(
        build_step, _stack_pt(ident), None, length=15
    )
    table = jnp.concatenate(
        [_stack_pt(curve.to_cached(ident))[None], entries], axis=0
    )  # (16, 4, 20, N)

    bt = jnp.asarray(_b_table())  # (16, 3, 20) int32 host consts

    def body(i, q):
        j = 63 - i
        d_s = lax.dynamic_index_in_dim(ds, j, 0, keepdims=False)
        d_h = lax.dynamic_index_in_dim(dh, j, 0, keepdims=False)
        q = curve.double(
            curve.double(
                curve.double(curve.double(q, need_t=False), need_t=False),
                need_t=False,
            )
        )
        idx = jnp.broadcast_to(
            d_h[None, None, None], (1,) + table.shape[1:]
        )
        ac = jnp.take_along_axis(table, idx, axis=0)[0]  # (4, 20, N)
        q = curve.add_cached(q, _unstack_pt(ac))
        ab = jnp.take(bt, d_s, axis=0)  # (N, 3, 20)
        addend_b = tuple(
            tuple(ab[..., k, lj] for lj in range(fe.NLIMBS))
            for k in range(3)
        )
        return curve.add_affine_cached(q, addend_b, need_t=False)

    return lax.fori_loop(0, 64, body, ident[:3] + (None,))


def _verify_core(msgs, lens, pks, rs, ss):
    """msgs (cap, N) uint8; lens (N,) int32; pks/rs/ss (32, N) uint8.

    Returns bool (N,): per-signature ZIP-215 verdicts.
    """
    cap = msgs.shape[0]
    n = pks.shape[1]
    # one decompression over [pks | rs]: the square-root exponentiation
    # is a ~254-deep sequential squaring chain whose cost is dominated
    # by depth, not lane count — sharing it across both points halves
    # that depth instead of paying it twice
    both, ok_both = curve.decompress(
        jnp.concatenate([pks, rs], axis=1)
    )
    A = tuple(tuple(c[:n] for c in comp) for comp in both)
    R = tuple(tuple(c[n:] for c in comp) for comp in both)
    ok_a, ok_r = ok_both[:n], ok_both[n:]
    s = fe.from_bytes_256(ss)
    ok_s = sc.lt_L(s)

    hin = jnp.concatenate([rs, pks, msgs], axis=0)
    digest = sha512.sha512(hin, lens + 64, cap + 64)
    h = sc.reduce_512(sc.hash_bytes_to_limbs(digest))
    hneg = sc.neg_mod_L(h)

    q = _straus(sc.digits4(s), sc.digits4(hneg), A, (n,))
    p8 = curve.mul_by_cofactor(
        curve.add_projective(q, (fe.neg(R[0]), R[1], R[2]))
    )
    return ok_a & ok_r & ok_s & curve.is_identity(p8)


def _verify_core_precomp(msgs, lens, a_arr, pks, rs, ss):
    """Verify with HOST-decompressed public keys (the expanded-pubkey
    LRU, reference crypto/ed25519/ed25519.go:31, moved on-device).

    a_arr (4, 20, N) int32: A in affine-extended limb form (x, y, 1,
    x*y), produced once per distinct key by the host cache. Validator
    sets repeat across blocks — a 10k-block replay has ~150 distinct
    keys for ~1.5M lanes — so only R still pays the ~254-deep sqrt
    chain, halving the decompression stage's depth-dominated cost.
    pks is still an input: the hash is SHA-512(R || A_bytes || M).

    Delegates to the tuple-form body after unpacking the stacked A —
    ONE verification body serves both dispatch modes (the modes must
    stay bit-identical; tests assert it).
    """
    A = tuple(
        tuple(a_arr[k, j] for j in range(fe.NLIMBS)) for k in range(4)
    )
    return _verify_core_precomp_tuple(msgs, lens, A, pks, rs, ss)


def _ladder_backend_key() -> tuple:
    """Everything the traced verify program branches on at TRACE time:
    ladder backend (pallas opt-in), field mode (compact vs tuple), and
    the pallas sublane blocking. The jit wrappers below are cached PER
    KEY, so flipping GRAFT_PALLAS / GRAFT_COMPACT_FIELD /
    GRAFT_PALLAS_SUBLANES mid-process retraces instead of silently
    reusing a stale trace (VERDICT r4 weak #6 — the bench no longer
    needs a subprocess per backend for correctness, only for compile-
    hang isolation)."""
    from .pallas_ladder import block_sublanes, min_lanes, pallas_enabled

    # pallas_enabled(None) here = "may pallas engage at SOME width";
    # the actual per-width choice lives in _straus and re-keys via the
    # per-shape jit trace, so min_lanes() must key the wrapper too
    pallas = pallas_enabled()
    return (
        "pallas" if pallas else "xla",
        fe.compact_mode(),
        block_sublanes() if pallas else 0,
        min_lanes() if pallas else 0,
    )


def _verify_core_precomp_tuple(msgs, lens, a_tree, pks, rs, ss):
    """Precomp verify with A handed over as a PYTREE of 80 separate
    (N,) int32 arrays instead of one stacked (4, 20, N) input
    (docs/PERF.md lever #6, round-5). The stacked form loses at bulk
    widths (550 vs 363 ms @131072) because slicing it back apart
    defeats tuple-of-limbs fusion; jit boundaries accept pytrees, so
    this variant preserves the tuple form end to end while still
    skipping A's half of the depth-bound sqrt chain. Opt-in via
    GRAFT_PRECOMP_TUPLE=1 pending a silicon A/B (not shipped blind).
    """
    cap = msgs.shape[0]
    n = rs.shape[1]
    A = a_tree
    R, ok_r = curve.decompress(rs)
    s = fe.from_bytes_256(ss)
    ok_s = sc.lt_L(s)

    hin = jnp.concatenate([rs, pks, msgs], axis=0)
    digest = sha512.sha512(hin, lens + 64, cap + 64)
    h = sc.reduce_512(sc.hash_bytes_to_limbs(digest))
    hneg = sc.neg_mod_L(h)

    q = _straus(sc.digits4(s), sc.digits4(hneg), A, (n,))
    p8 = curve.mul_by_cofactor(
        curve.add_projective(q, (fe.neg(R[0]), R[1], R[2]))
    )
    return ok_r & ok_s & curve.is_identity(p8)


def precomp_tuple_enabled() -> bool:
    return os.environ.get("GRAFT_PRECOMP_TUPLE") == "1"


def a_tree_from_stacked(a_arr):
    """Host-side: stacked (4, NLIMBS, N) numpy A -> the pytree of 80
    separate (N,) device arrays the tuple kernel takes. The ONE
    builder production and bench share, so the A/B leg measures the
    exact input form production dispatches."""
    return tuple(
        tuple(
            jnp.asarray(np.ascontiguousarray(a_arr[k, j]))
            for j in range(fe.NLIMBS)
        )
        for k in range(4)
    )


def _precomp_max_lanes() -> int:
    """Width cutoff for the precomp kernel; env-overridable so the
    bench can force precomp at bulk widths for the lever-#6 A/B."""
    v = os.environ.get("GRAFT_PRECOMP_MAX_LANES")
    return int(v) if v else PRECOMP_MAX_LANES


@functools.lru_cache(maxsize=None)
def _keyed_jit(kind: str, key: tuple):
    core = {
        "plain": _verify_core,
        "precomp": _verify_core_precomp,
        "precomp_tuple": _verify_core_precomp_tuple,
    }[kind]
    return jax.jit(core)


def verify_core_jit(msgs, lens, pks, rs, ss):
    return _keyed_jit("plain", _ladder_backend_key())(
        msgs, lens, pks, rs, ss
    )


def verify_core_precomp_jit(msgs, lens, a_arr, pks, rs, ss):
    return _keyed_jit("precomp", _ladder_backend_key())(
        msgs, lens, a_arr, pks, rs, ss
    )


def verify_core_precomp_tuple_jit(msgs, lens, a_tree, pks, rs, ss):
    return _keyed_jit("precomp_tuple", _ladder_backend_key())(
        msgs, lens, a_tree, pks, rs, ss
    )


# --- host-side expanded-pubkey cache -----------------------------------
# pk bytes -> (4, 20) int32 affine-extended limbs, or None for keys
# that fail ZIP-215 decompression. LRU, like the reference's expanded
# ed25519 key cache (crypto/ed25519/ed25519.go:31).
_A_CACHE: "dict" = {}
_A_CACHE_MAX = 4096


def _expand_pubkey(pk: bytes):
    if pk in _A_CACHE:
        return _A_CACHE[pk]
    from ..crypto import ref_ed25519 as _ref

    pt = _ref.point_decompress(pk)
    if pt is None:
        val = None
    else:
        x, y, _z, t = pt
        val = np.stack(
            [
                fe.raw_limbs(x),
                fe.raw_limbs(y),
                fe.raw_limbs(1),
                fe.raw_limbs(t),
            ]
        )  # (4, 20) int32
    if len(_A_CACHE) >= _A_CACHE_MAX:
        _A_CACHE.pop(next(iter(_A_CACHE)))
    _A_CACHE[pk] = val
    return val


# minimum lane padding; shrunk by the multichip dryrun so its one
# kernel compile happens at tiny per-device shapes
PAD_MIN = 128

# Width cutoff between the two kernels (measured on v5e, uncontended):
# - small batches: the ~254-deep decompression chain dominates, so the
#   precomp kernel (host-expanded A, only R pays the sqrt chain) wins;
# - large batches: depth amortizes across lanes and the precomp path's
#   stacked (4,20,N) A input costs MORE than it saves (slice reads
#   defeat the tuple-of-limbs fusion: 550ms vs 363ms @131072 lanes).
PRECOMP_MAX_LANES = 4096


def _pad_n(n: int) -> int:
    """Pad batch to limit recompilation: powers of two >= PAD_MIN."""
    p = PAD_MIN
    while p < n:
        p *= 2
    return p


# Mesh-aware dispatch: when more than one local device is visible the
# batch is lane-sharded over all of them (data parallelism over
# signature lanes — the framework's ICI scaling axis, SURVEY.md §2.2).
# Keyed by device count; jitted shard_map programs are cached here.
_SHARDED_FNS: dict = {}

# Introspection for tests/dryrun: how the last verify_batch dispatched.
LAST_DISPATCH: dict = {}


def _sharded_fn(mode: str):
    """(n_devices, fn): lane-sharded verify over all local devices, or
    (1, None) when single-device / uninitializable backend. ``mode``:
    "plain" | "precomp" | "precomp_tuple"."""
    try:
        n = len(jax.devices())
    except Exception:  # pragma: no cover - backend init failure
        return 1, None
    if n <= 1:
        return 1, None
    # backend key: the sharded program traces through _straus too, so
    # a mid-process backend flip must map to a fresh shard_map program
    key = (n, mode, _ladder_backend_key())
    if key not in _SHARDED_FNS:
        from ..parallel.mesh import make_mesh
        from ..parallel.sharded_verify import make_sharded_core

        _SHARDED_FNS[key] = make_sharded_core(make_mesh(n), mode)
    return n, _SHARDED_FNS[key]


class AsyncVerdicts:
    """Handle for an in-flight verify dispatch (XLA dispatch is async:
    the program is enqueued and this handle holds the device future).
    ``result()`` blocks and returns the bool verdicts. Overlapping
    several dispatches before resolving amortizes the per-dispatch
    link latency — the production pipelining seam (bench config
    "pipeline")."""

    def __init__(self, res, bad, n):
        self._res = res
        self._bad = bad
        self._n = n

    def wait(self) -> "AsyncVerdicts":
        """Block until the device computation is READY, without
        fetching the verdicts to host (thread-safe; used by the
        routing calibration's readiness watcher in crypto/batch)."""
        bur = getattr(self._res, "block_until_ready", None)
        if bur is not None:
            bur()
        return self

    def wait_fetch(self) -> "AsyncVerdicts":
        """Block until the result is GENUINELY available by fetching a
        single element to host. On the tunneled (axon) platform
        block_until_ready returns without blocking (the readiness
        query doesn't round-trip the link — bench.py platform note),
        so wait() under-reports dispatch walls; a 1-element fetch must
        complete the round trip. The fetched slice is a fresh tiny
        computation, so the full verdict array is not pulled over the
        link (thread-safe; used by the calibration watcher)."""
        res = self._res
        if self._n and getattr(res, "ndim", 0) == 1:
            np.asarray(res[:1])
            return self
        return self.wait()

    def result(self) -> np.ndarray:
        out = np.array(self._res)[: self._n]
        out[self._bad[: self._n]] = False
        return out


def verify_batch_async(items) -> AsyncVerdicts:
    """Enqueue one verify dispatch WITHOUT blocking on the verdicts
    (see AsyncVerdicts). Same prep/dispatch as verify_batch."""
    n = len(items)
    if n == 0:
        return AsyncVerdicts(np.zeros(0, bool), np.zeros(0, bool), 0)
    max_len = max(len(m) for m, _, _ in items)
    cap = bucket_cap(max_len)
    np_ = _pad_n(n)
    n_dev, probe = _sharded_fn("precomp")
    if probe is not None and np_ % n_dev:
        np_ += n_dev - (np_ % n_dev)

    # kernel choice by PER-DEVICE lane width (see PRECOMP_MAX_LANES):
    # precomp (host-expanded A) below the cutoff — the depth-bound
    # decompression dominates there — plain above it, where depth
    # amortizes and the stacked A input costs more than it saves
    # (unless the tuple-form A opt-in is on, docs/PERF.md lever #6)
    use_precomp = (np_ // n_dev) <= _precomp_max_lanes()
    tuple_a = use_precomp and precomp_tuple_enabled()
    mode = (
        "precomp_tuple"
        if tuple_a
        else ("precomp" if use_precomp else "plain")
    )
    sharded = None
    if probe is not None:
        _, sharded = _sharded_fn(mode)

    msgs = np.zeros((cap, np_), np.uint8)
    lens = np.zeros(np_, np.int32)
    pks = np.zeros((32, np_), np.uint8)
    rs = np.zeros((32, np_), np.uint8)
    ss = np.zeros((32, np_), np.uint8)
    a_arr = (
        np.zeros((4, fe.NLIMBS, np_), np.int32) if use_precomp else None
    )
    bad = np.zeros(np_, bool)
    for i, (m, pk, sig) in enumerate(items):
        if len(pk) != 32 or len(sig) != 64:
            bad[i] = True
            continue
        if use_precomp:
            A = _expand_pubkey(bytes(pk))
            if A is None:  # pubkey fails ZIP-215 decompression
                bad[i] = True
                continue
            a_arr[:, :, i] = A
        msgs[: len(m), i] = np.frombuffer(m, np.uint8)
        lens[i] = len(m)
        pks[:, i] = np.frombuffer(pk, np.uint8)
        rs[:, i] = np.frombuffer(sig[:32], np.uint8)
        ss[:, i] = np.frombuffer(sig[32:], np.uint8)

    # backend_key[0] reports the ladder the kernel ACTUALLY uses at
    # this dispatch's per-device width (pallas engages by default only
    # at bulk widths — pallas_ladder.min_lanes — and only on
    # 128-multiple lanes), not merely whether pallas may engage
    from .pallas_ladder import pallas_enabled as _pallas_on

    lane_w = np_ // n_dev
    eff_pallas = lane_w % 128 == 0 and _pallas_on(lane_w)
    LAST_DISPATCH.clear()
    LAST_DISPATCH.update(
        sharded=sharded is not None,
        n_devices=n_dev,
        lanes=np_,
        cap=cap,
        precomp=use_precomp,
        mode=mode,
        backend_key=("pallas" if eff_pallas else "xla",)
        + _ladder_backend_key()[1:],
    )
    if tuple_a:
        # pytree A: 80 separate (N,) arrays, preserving tuple-of-limbs
        # fusion across the jit boundary (lever #6)
        a_tree = a_tree_from_stacked(a_arr)
        fn = (
            sharded
            if sharded is not None
            else verify_core_precomp_tuple_jit
        )
        res = fn(
            jnp.asarray(msgs),
            jnp.asarray(lens),
            a_tree,
            jnp.asarray(pks),
            jnp.asarray(rs),
            jnp.asarray(ss),
        )
        return AsyncVerdicts(res, bad, n)
    if use_precomp:
        fn = sharded if sharded is not None else verify_core_precomp_jit
        arrays = (msgs, lens, a_arr, pks, rs, ss)
    else:
        fn = sharded if sharded is not None else verify_core_jit
        arrays = (msgs, lens, pks, rs, ss)
    res = fn(*(jnp.asarray(a) for a in arrays))
    return AsyncVerdicts(res, bad, n)


def verify_batch(items) -> np.ndarray:
    """Host API: items = list of (msg: bytes, pubkey: 32B, sig: 64B).

    Returns np.ndarray of bool verdicts, one per item. Builds padded
    device arrays (batch-last layout), dispatches one XLA program —
    lane-sharded over every local device when a multi-chip mesh is
    available (same shard_map program the driver dryrun validates).

    Public keys are decompressed ONCE per distinct key on the host
    (LRU) and fed to the kernel in limb form: validator sets repeat
    across commits, so the device-side sqrt chain only runs for the R
    points (the reference's expanded-key LRU, ed25519.go:31).
    """
    return verify_batch_async(items).result()
