"""Pallas (Mosaic) Straus ladder: VMEM-resident window tables.

The double-scalar ladder is ~60% of the verify kernel's runtime
(docs/PERF.md ablations). Under plain XLA the per-lane 16-entry window
table (5.1 KB/lane) streams through HBM on every one of the 64 windows
— ~43 GB of table traffic per 131072-lane dispatch — because each
field element is ~10.5 MB at bulk widths and nothing fits in VMEM
across windows. This kernel blocks the lanes so that, per grid step,
the table slice, the digit planes and the accumulator point all live
in VMEM for the whole 64-window loop: table bytes move from HBM once
per dispatch instead of 64 times, and Mosaic schedules the double/add
chains directly.

The field math inside the kernel body is the SAME tuple-of-limbs code
as the XLA path (ops/fe25519, ops/curve25519) — limbs are (S, 128)
int32 tiles sliced from VMEM refs, and every op is elementwise on
them, which is exactly what the VPU wants. The window schedule is
identical to ops/ed25519._straus, so verdicts are bit-identical.

Replaces the hot loop behind the reference's batch-verification seam
(curve25519-voi Straus ladder used by crypto/ed25519 verification);
an original design for the TPU memory hierarchy, not a port.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve25519 as curve
from . import fe25519 as fe

# lanes per grid step = block_sublanes() * 128. Mosaic requires the
# sublane (second-to-minor) block dim to be a multiple of 8 — or the
# whole array dim — so 8 sublanes (1024 lanes) is the FLOOR at bulk
# widths, not a tuning choice; the r5 first-contact sweep's 4-sublane
# leg failed lowering on exactly that check
# (jax/_src/pallas/mosaic/lowering.py:_check_block_mappings). At 8
# sublanes the table slice is 5.2 MB; with Pallas's default
# double-buffering plus digit planes the working set fits the ~16 MB
# VMEM budget (compiles and runs on v5e silicon, r5). Bench-tunable
# via GRAFT_PALLAS_SUBLANES; tests may pin the module attribute.
BLOCK_SUBLANES = None  # None = read GRAFT_PALLAS_SUBLANES (default 8)


def block_sublanes() -> int:
    if BLOCK_SUBLANES is not None:
        return BLOCK_SUBLANES
    return int(os.environ.get("GRAFT_PALLAS_SUBLANES", "8"))


def min_lanes() -> int:
    """Width floor for the default-on pallas ladder (bulk widths
    only). Measured on v5e silicon (r5 first contact, docs/PERF.md):
    at 131072 lanes the VMEM ladder is 2.5x the XLA ladder (801k vs
    320k verifies/s); at replay widths (<=32768 lanes) both are
    dispatch/transfer-bound and indistinguishable in steady state,
    while the Mosaic compile is ~10x costlier per lane bucket
    (~7-9 min vs ~40 s) and the persistent compilation cache cannot
    amortize it (nondeterministic program fingerprint, see PERF.md) —
    so small widths stay on the XLA ladder by default."""
    return int(os.environ.get("GRAFT_PALLAS_MIN_LANES", "65536"))


@functools.lru_cache(maxsize=1)
def _accelerator_backend() -> bool:
    """Is the default jax backend a real accelerator? Memoized: the
    backend identity cannot change once initialized in-process (the
    env knobs that CAN flip mid-process are read dynamically and are
    part of ops/ed25519._ladder_backend_key)."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def pallas_enabled(n: "int | None" = None) -> bool:
    """Ladder backend selection, r5-measured policy: GRAFT_PALLAS=1
    forces pallas at every width (tests, A/B legs), GRAFT_PALLAS=0
    forces the XLA ladder; otherwise pallas is the DEFAULT on
    accelerator backends at bulk widths (n >= min_lanes(), where the
    r5 silicon A/B measured 2.5x) and off elsewhere. Read dynamically
    AND safely flippable mid-process: the verify jit wrappers are
    keyed by (ladder backend, field mode, sublanes, min-lanes) —
    ops/ed25519._ladder_backend_key — so an env flip reaches the next
    verify_batch instead of silently hitting a stale cached trace
    (VERDICT r4 weak #6)."""
    v = os.environ.get("GRAFT_PALLAS")
    if v == "1":
        return True
    if v == "0":
        return False
    if n is not None and n < min_lanes():
        return False
    return _accelerator_backend()


def _tree_select16(digit, entries):
    """16-way table lookup as a 4-level binary select tree.

    Mosaic's select_n lowering only supports 2 cases
    (jax/_src/pallas/mosaic/lowering.py:_select_n_lowering_rule — the
    bench's first silicon contact failed exactly there), so the
    window-digit lookup selects on one digit bit per level: entry
    index d = b0 + 2*b1 + 4*b2 + 8*b3. Same function as
    lax.select_n(digit, *entries); 15 two-way selects per limb."""
    lvl = list(entries)
    for k in range(4):
        bit = lax.shift_right_logical(digit, k) & 1
        pred = bit != 0
        lvl = [
            lax.select_n(pred, lvl[2 * i], lvl[2 * i + 1])
            for i in range(len(lvl) // 2)
        ]
    return lvl[0]


def _ladder_kernel(ds_ref, dh_ref, table_ref, out_ref):
    """One lane block: table_ref (16, 4, 20, S, 128) VMEM; ds/dh
    (64, S, 128); out_ref (3, 20, S, 128) = X, Y, Z of the ladder
    result (T-less carry, same as _straus)."""
    s = table_ref.shape[3]
    shape = (s, 128)
    ident = curve.identity(shape)

    # B window table: shared host constants, broadcast per lane
    from .ed25519 import _b_table

    bt = _b_table()  # numpy (16, 3, 20)

    def body(i, q):
        j = 63 - i
        d_s = ds_ref[j]
        d_h = dh_ref[j]
        q = curve.double(
            curve.double(
                curve.double(curve.double(q, need_t=False), need_t=False),
                need_t=False,
            )
        )
        addend_a = tuple(
            tuple(
                _tree_select16(
                    d_h, [table_ref[d, k, lj] for d in range(16)]
                )
                for lj in range(fe.NLIMBS)
            )
            for k in range(4)
        )
        q = curve.add_cached(q, addend_a)
        addend_b = tuple(
            tuple(
                _tree_select16(
                    d_s,
                    [
                        jnp.full(shape, int(bt[d, k, lj]), jnp.int32)
                        for d in range(16)
                    ],
                )
                for lj in range(fe.NLIMBS)
            )
            for k in range(3)
        )
        return curve.add_affine_cached(q, addend_b, need_t=False)

    q = lax.fori_loop(0, 64, body, ident[:3] + (None,))
    for k in range(3):
        for lj in range(fe.NLIMBS):
            out_ref[k, lj] = q[k][lj]


def effective_block(block: int, r: int) -> "int | None":
    """The sublane-block height the kernel will actually run for a
    configured ``block`` over ``r`` sublane rows, or None when no
    VMEM-safe Mosaic-valid blocking exists (caller falls back to the
    XLA ladder).

    Constraints (r5 silicon contact): the height must DIVIDE r (a
    remainder block would silently drop rows — uninitialized verdict
    lanes, code-review r4), and Mosaic requires it to be a multiple
    of 8 OR the whole dim. The fallback never grows past
    max(block, 8): the whole-dim escape at large odd r would build an
    unbounded VMEM block (r=513 -> a ~333 MB table slice) — an
    explicitly configured larger block is honored (the operator is
    sweeping), but the automatic fallback stays at proven sizes."""
    cap = max(block, 8)
    best = None
    for d in range(8, min(r, cap) + 1, 8):
        if r % d == 0:
            best = d
    if best is not None:
        return best
    if r <= cap:
        return r  # whole dim (== r) is Mosaic-valid and small
    return None


@functools.partial(
    jax.jit, static_argnames=("block", "interpret")
)
def _ladder_call(ds, dh, table, block=8, interpret=False):
    """ds/dh (64, R, 128) int32; table (16, 4, 20, R, 128) int32 ->
    (3, 20, R, 128) int32 (X, Y, Z tuple-of-limbs, carried).

    ``block`` is the EFFECTIVE sublane-block height (the caller runs
    effective_block() first) and is a STATIC arg: it shapes the grid,
    so it must key this function's own jit cache — a mid-process
    GRAFT_PALLAS_SUBLANES change then retraces instead of silently
    reusing the old blocking."""
    r = ds.shape[1]
    s = block
    assert r % s == 0 and (s % 8 == 0 or s == r), (s, r)
    grid = (r // s,)
    return pl.pallas_call(
        _ladder_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (64, s, 128), lambda i: (0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (64, s, 128), lambda i: (0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (16, 4, fe.NLIMBS, s, 128),
                lambda i: (0, 0, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (3, fe.NLIMBS, s, 128),
            lambda i: (0, 0, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (3, fe.NLIMBS, r, 128), jnp.int32
        ),
        interpret=interpret,
    )(ds, dh, table)


def straus_pallas(ds, dh, A, shape, interpret=None):
    """Drop-in for ops/ed25519._straus on lane counts that are
    multiples of 128: [s]B + [hneg]A via the VMEM-blocked kernel.

    ds/dh: (64, N) digit planes; A: tuple-form extended point; returns
    the T-less (X, Y, Z, None) tuple-of-limbs point, matching _straus.
    The per-lane A window table is built in XLA (15 sequential cached
    adds, the same build as _straus) and handed to the kernel stacked —
    built once, read once from HBM, resident in VMEM for all windows.

    interpret=None auto-selects: the Pallas interpreter on the CPU
    backend (Mosaic needs real hardware), compiled Mosaic elsewhere —
    so the GRAFT_PALLAS backend flip is exercisable on any platform.

    Returns None when no VMEM-safe blocking exists for this width
    (effective_block) — the caller (ops/ed25519._straus) falls back
    to the XLA ladder rather than building an unbounded VMEM block.
    """
    (n,) = shape
    assert n % 128 == 0, n
    r = n // 128
    s = effective_block(block_sublanes(), r)
    if s is None:
        return None
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    ext = curve.identity(shape)
    entries = [curve.to_cached(ext)]
    acc = ext
    for _ in range(15):
        acc = curve.add(acc, A)
        entries.append(curve.to_cached(acc))
    table = jnp.stack(
        [
            jnp.stack([fe.stack(comp) for comp in e])
            for e in entries
        ]
    )  # (16, 4, 20, N)

    table = table.reshape(16, 4, fe.NLIMBS, r, 128)
    ds_t = ds.reshape(64, r, 128)
    dh_t = dh.reshape(64, r, 128)
    # the EFFECTIVE block, not the configured one: _ladder_call's
    # divisor assert rejects any configured value that doesn't divide
    # r (ADVICE r5 high — N=128 under GRAFT_PALLAS=1 tripped it)
    out = _ladder_call(
        ds_t, dh_t, table,
        block=s, interpret=interpret,
    )
    out = out.reshape(3, fe.NLIMBS, n)
    return (
        tuple(out[0, i] for i in range(fe.NLIMBS)),
        tuple(out[1, i] for i in range(fe.NLIMBS)),
        tuple(out[2, i] for i in range(fe.NLIMBS)),
        None,
    )
