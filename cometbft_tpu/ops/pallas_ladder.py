"""Pallas (Mosaic) Straus ladder: VMEM-resident window tables.

The double-scalar ladder is ~60% of the verify kernel's runtime
(docs/PERF.md ablations). Under plain XLA the per-lane 16-entry window
table (5.1 KB/lane) streams through HBM on every one of the 64 windows
— ~43 GB of table traffic per 131072-lane dispatch — because each
field element is ~10.5 MB at bulk widths and nothing fits in VMEM
across windows. This kernel blocks the lanes so that, per grid step,
the table slice, the digit planes and the accumulator point all live
in VMEM for the whole 64-window loop: table bytes move from HBM once
per dispatch instead of 64 times, and Mosaic schedules the double/add
chains directly.

The field math inside the kernel body is the SAME tuple-of-limbs code
as the XLA path (ops/fe25519, ops/curve25519) — limbs are (S, 128)
int32 tiles sliced from VMEM refs, and every op is elementwise on
them, which is exactly what the VPU wants. The window schedule is
identical to ops/ed25519._straus, so verdicts are bit-identical.

Replaces the hot loop behind the reference's batch-verification seam
(curve25519-voi Straus ladder used by crypto/ed25519 verification);
an original design for the TPU memory hierarchy, not a port.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve25519 as curve
from . import fe25519 as fe

# lanes per grid step = block_sublanes() * 128. At 4 sublanes (512
# lanes) the table slice is 2.6 MB — with Pallas's default
# double-buffering of input/output blocks plus digit planes and the
# working set that stays well inside the ~16 MB VMEM budget; 8
# sublanes doubles table residency and may not (untested on silicon —
# the platform was down all round 4), so the default is the safe one.
# Bench-tunable via GRAFT_PALLAS_SUBLANES; tests may pin the module
# attribute directly.
BLOCK_SUBLANES = None  # None = read GRAFT_PALLAS_SUBLANES (default 4)


def block_sublanes() -> int:
    if BLOCK_SUBLANES is not None:
        return BLOCK_SUBLANES
    return int(os.environ.get("GRAFT_PALLAS_SUBLANES", "4"))


def pallas_enabled() -> bool:
    """Ladder backend selection: GRAFT_PALLAS=1 opts in; default off
    until the Pallas path is driver-benchmarked faster (bench.py
    measures both and records the ablation in docs/PERF.md). Read
    dynamically AND safely flippable mid-process: the verify jit
    wrappers are keyed by (ladder backend, field mode, sublanes) —
    ops/ed25519._ladder_backend_key — so an env flip reaches the next
    verify_batch instead of silently hitting a stale cached trace
    (VERDICT r4 weak #6)."""
    return os.environ.get("GRAFT_PALLAS") == "1"


def _ladder_kernel(ds_ref, dh_ref, table_ref, out_ref):
    """One lane block: table_ref (16, 4, 20, S, 128) VMEM; ds/dh
    (64, S, 128); out_ref (3, 20, S, 128) = X, Y, Z of the ladder
    result (T-less carry, same as _straus)."""
    s = table_ref.shape[3]
    shape = (s, 128)
    ident = curve.identity(shape)

    # B window table: shared host constants, broadcast per lane
    from .ed25519 import _b_table

    bt = _b_table()  # numpy (16, 3, 20)

    def body(i, q):
        j = 63 - i
        d_s = ds_ref[j]
        d_h = dh_ref[j]
        q = curve.double(
            curve.double(
                curve.double(curve.double(q, need_t=False), need_t=False),
                need_t=False,
            )
        )
        addend_a = tuple(
            tuple(
                lax.select_n(
                    d_h, *[table_ref[d, k, lj] for d in range(16)]
                )
                for lj in range(fe.NLIMBS)
            )
            for k in range(4)
        )
        q = curve.add_cached(q, addend_a)
        addend_b = tuple(
            tuple(
                lax.select_n(
                    d_s,
                    *[
                        jnp.full(shape, int(bt[d, k, lj]), jnp.int32)
                        for d in range(16)
                    ],
                )
                for lj in range(fe.NLIMBS)
            )
            for k in range(3)
        )
        return curve.add_affine_cached(q, addend_b, need_t=False)

    q = lax.fori_loop(0, 64, body, ident[:3] + (None,))
    for k in range(3):
        for lj in range(fe.NLIMBS):
            out_ref[k, lj] = q[k][lj]


@functools.partial(
    jax.jit, static_argnames=("block", "interpret")
)
def _ladder_call(ds, dh, table, block=4, interpret=False):
    """ds/dh (64, R, 128) int32; table (16, 4, 20, R, 128) int32 ->
    (3, 20, R, 128) int32 (X, Y, Z tuple-of-limbs, carried).

    ``block`` (the configured sublane-block height) is a STATIC arg:
    it shapes the grid, so it must key this function's own jit cache —
    a mid-process GRAFT_PALLAS_SUBLANES change then retraces instead
    of silently reusing the old blocking."""
    r = ds.shape[1]
    # block height must DIVIDE the sublane-row count or the grid would
    # silently drop the remainder rows (uninitialized verdict lanes):
    # take the largest divisor of r that fits the configured block
    s = min(block, r)
    while r % s:
        s -= 1
    grid = (r // s,)
    return pl.pallas_call(
        _ladder_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (64, s, 128), lambda i: (0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (64, s, 128), lambda i: (0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (16, 4, fe.NLIMBS, s, 128),
                lambda i: (0, 0, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (3, fe.NLIMBS, s, 128),
            lambda i: (0, 0, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (3, fe.NLIMBS, r, 128), jnp.int32
        ),
        interpret=interpret,
    )(ds, dh, table)


def straus_pallas(ds, dh, A, shape, interpret=None):
    """Drop-in for ops/ed25519._straus on lane counts that are
    multiples of 128: [s]B + [hneg]A via the VMEM-blocked kernel.

    ds/dh: (64, N) digit planes; A: tuple-form extended point; returns
    the T-less (X, Y, Z, None) tuple-of-limbs point, matching _straus.
    The per-lane A window table is built in XLA (15 sequential cached
    adds, the same build as _straus) and handed to the kernel stacked —
    built once, read once from HBM, resident in VMEM for all windows.

    interpret=None auto-selects: the Pallas interpreter on the CPU
    backend (Mosaic needs real hardware), compiled Mosaic elsewhere —
    so the GRAFT_PALLAS backend flip is exercisable on any platform.
    """
    (n,) = shape
    assert n % 128 == 0, n
    r = n // 128
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    ext = curve.identity(shape)
    entries = [curve.to_cached(ext)]
    acc = ext
    for _ in range(15):
        acc = curve.add(acc, A)
        entries.append(curve.to_cached(acc))
    table = jnp.stack(
        [
            jnp.stack([fe.stack(comp) for comp in e])
            for e in entries
        ]
    )  # (16, 4, 20, N)

    table = table.reshape(16, 4, fe.NLIMBS, r, 128)
    ds_t = ds.reshape(64, r, 128)
    dh_t = dh.reshape(64, r, 128)
    out = _ladder_call(
        ds_t, dh_t, table,
        block=block_sublanes(), interpret=interpret,
    )
    out = out.reshape(3, fe.NLIMBS, n)
    return (
        tuple(out[0, i] for i in range(fe.NLIMBS)),
        tuple(out[1, i] for i in range(fe.NLIMBS)),
        tuple(out[2, i] for i in range(fe.NLIMBS)),
        None,
    )
