"""SHA-512 for TPU lanes: 64-bit words as (hi, lo) uint32 pairs.

TPU has no 64-bit integers; every 64-bit word is a pair of uint32 arrays
(hi, lo), batch on the trailing axes (convention: byte/word axis first,
batch last — see fe25519 layout note). Add-with-carry, rotates and the
sigma functions are expressed in uint32 lane ops; XLA fuses them.

Variable-length messages in fixed-capacity buffers: every lane runs the
same static number of compression rounds (`ceil((cap+17)/128)` blocks);
a lane's state stops updating after its own final block (branch-free
select), and padding/length bytes are injected positionally. This keeps
shapes/control flow static for XLA while supporting per-lane lengths.

Used by ed25519 verification: h = SHA-512(R || A || M) computed entirely
on device (reference seam: curve25519-voi's use of SHA-512 inside
crypto/ed25519 verify, reference crypto/ed25519/ed25519.go).

Round constants/IVs derived exactly via integer roots (FIPS 180-4).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax


def _iroot(x: int, n: int) -> int:
    """floor(x**(1/n)) by Newton on python ints (exact)."""
    if x == 0:
        return 0
    r = 1 << ((x.bit_length() + n - 1) // n)
    while True:
        nr = ((n - 1) * r + x // r ** (n - 1)) // n
        if nr >= r:
            return r
        r = nr


def _primes(n: int):
    ps, c = [], 2
    while len(ps) < n:
        if all(c % p for p in ps if p * p <= c):
            ps.append(c)
        c += 1
    return ps


def _frac_root_bits(p: int, root: int, bits: int = 64) -> int:
    whole = _iroot(p << (root * bits), root)
    return whole & ((1 << bits) - 1)


_K64 = [_frac_root_bits(p, 3) for p in _primes(80)]
_H64 = [_frac_root_bits(p, 2) for p in _primes(8)]

K_HI = np.asarray([k >> 32 for k in _K64], np.uint32)
K_LO = np.asarray([k & 0xFFFFFFFF for k in _K64], np.uint32)
H_HI = np.asarray([h >> 32 for h in _H64], np.uint32)
H_LO = np.asarray([h & 0xFFFFFFFF for h in _H64], np.uint32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _rotr64(h, l, n: int):
    if n == 32:
        return l, h
    if n < 32:
        m = 32 - n
        return (
            (h >> n) | (l << m),
            (l >> n) | (h << m),
        )
    n -= 32
    m = 32 - n
    return (
        (l >> n) | (h << m),
        (h >> n) | (l << m),
    )


def _shr64(h, l, n: int):
    if n < 32:
        return h >> n, (l >> n) | (h << (32 - n))
    return jnp.zeros_like(h), h >> (n - 32)


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def _big_sigma0(h, l):
    return _xor3(_rotr64(h, l, 28), _rotr64(h, l, 34), _rotr64(h, l, 39))


def _big_sigma1(h, l):
    return _xor3(_rotr64(h, l, 14), _rotr64(h, l, 18), _rotr64(h, l, 41))


def _small_sigma0(h, l):
    return _xor3(_rotr64(h, l, 1), _rotr64(h, l, 8), _shr64(h, l, 7))


def _small_sigma1(h, l):
    return _xor3(_rotr64(h, l, 19), _rotr64(h, l, 61), _shr64(h, l, 6))


def _compress(state, whi, wlo):
    """One SHA-512 compression via two lax.scans (schedule + rounds).

    state: tuple of 8 (hi, lo) pairs; whi/wlo: (16, N...) block words.
    Scans keep the HLO small (a statically unrolled 80-round body made
    XLA compile time explode and fused poorly)."""
    from jax import lax

    # message schedule: rolling 16-word window, 64 steps -> W[16..80)
    def sched(carry, _):
        wh, wl = carry  # (16, N...)
        s0 = _small_sigma0(wh[1], wl[1])
        s1 = _small_sigma1(wh[14], wl[14])
        h, l = _add64(wh[0], wl[0], *s0)
        h, l = _add64(h, l, *s1)
        h, l = _add64(h, l, wh[9], wl[9])
        wh = jnp.concatenate([wh[1:], h[None]], axis=0)
        wl = jnp.concatenate([wl[1:], l[None]], axis=0)
        return (wh, wl), (h, l)

    (_, _), (ext_h, ext_l) = lax.scan(sched, (whi, wlo), None, length=64)
    w_h = jnp.concatenate([whi, ext_h], axis=0)  # (80, N...)
    w_l = jnp.concatenate([wlo, ext_l], axis=0)

    k_h = jnp.asarray(K_HI)
    k_l = jnp.asarray(K_LO)
    kb = (1,) * (whi.ndim - 1)

    def round_(carry, xs):
        a, b, c, d, e, f, g, hh = carry
        wjh, wjl, kjh, kjl = xs
        t1 = _add64(hh[0], hh[1], *_big_sigma1(*e))
        ch = (
            (e[0] & f[0]) ^ (~e[0] & g[0]),
            (e[1] & f[1]) ^ (~e[1] & g[1]),
        )
        t1 = _add64(*t1, *ch)
        t1 = _add64(*t1, kjh.reshape(kb), kjl.reshape(kb))
        t1 = _add64(*t1, wjh, wjl)
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        t2 = _add64(*_big_sigma0(*a), *maj)
        return (
            (_add64(*t1, *t2), a, b, c, _add64(*d, *t1), e, f, g),
            None,
        )

    init = state
    final, _ = lax.scan(round_, init, (w_h, w_l, k_h, k_l))
    return tuple(
        (_add64(*old, *new)) for old, new in zip(state, final)
    )


def sha512(data, length, cap: int):
    """SHA-512 of per-lane variable-length messages.

    data:   (cap, N...) uint8, zero beyond each lane's length
    length: (N...) int32 message byte length (<= cap)
    cap:    static buffer capacity

    Returns digest as (64, N...) uint8 (standard big-endian word bytes).
    """
    nblocks = (cap + 17 + 127) // 128
    total = nblocks * 128
    data = data.astype(jnp.uint32)
    shape = data.shape[1:]
    if cap < total:
        data = jnp.concatenate(
            [data, jnp.zeros((total - cap,) + shape, jnp.uint32)], axis=0
        )
    pos = jnp.arange(total, dtype=jnp.int32).reshape(
        (total,) + (1,) * len(shape)
    )
    ln = length[None].astype(jnp.int32)
    msk = (pos < ln).astype(jnp.uint32)
    buf = data * msk + jnp.where(pos == ln, jnp.uint32(0x80), 0)
    # 128-bit big-endian bit length: only low 4 bytes can be nonzero
    final_block = (ln + 16) // 128  # block index holding the length field
    bitlen = (ln * 8).astype(jnp.uint32)
    for s in range(4):
        at = final_block * 128 + 124 + s
        buf = buf + jnp.where(
            pos == at, (bitlen >> jnp.uint32(8 * (3 - s))) & 0xFF, 0
        )

    state = tuple(
        (
            jnp.broadcast_to(jnp.uint32(H_HI[i]), shape),
            jnp.broadcast_to(jnp.uint32(H_LO[i]), shape),
        )
        for i in range(8)
    )
    for blk in range(nblocks):
        base = blk * 128
        whi = jnp.stack(
            [
                (buf[base + 8 * w] << 24)
                | (buf[base + 8 * w + 1] << 16)
                | (buf[base + 8 * w + 2] << 8)
                | buf[base + 8 * w + 3]
                for w in range(16)
            ],
            axis=0,
        )
        wlo = jnp.stack(
            [
                (buf[base + 8 * w + 4] << 24)
                | (buf[base + 8 * w + 5] << 16)
                | (buf[base + 8 * w + 6] << 8)
                | buf[base + 8 * w + 7]
                for w in range(16)
            ],
            axis=0,
        )
        new_state = _compress(state, whi, wlo)
        active = blk <= final_block[0]  # (N...) bool
        state = tuple(
            (
                jnp.where(active, nh, oh),
                jnp.where(active, nl, ol),
            )
            for (nh, nl), (oh, ol) in zip(new_state, state)
        )

    out = []
    for i in range(8):
        h, l = state[i]
        out.extend(
            [
                (h >> 24) & 0xFF,
                (h >> 16) & 0xFF,
                (h >> 8) & 0xFF,
                h & 0xFF,
                (l >> 24) & 0xFF,
                (l >> 16) & 0xFF,
                (l >> 8) & 0xFF,
                l & 0xFF,
            ]
        )
    return jnp.stack(out, axis=0).astype(jnp.uint8)
