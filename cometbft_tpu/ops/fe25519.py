"""GF(2^255 - 19) field arithmetic for TPU, vectorized over batch lanes.

Design notes (TPU-first, not a port):

* TPU has no 64-bit integers and no big-int unit. A field element is a
  **tuple of ``NLIMBS = 20`` separate int32 arrays** (one per 13-bit
  limb), each shaped ``(N...)`` with the batch on the trailing axes.
  The tuple-of-arrays form (rather than one stacked ``(20, N)`` array)
  is the load-bearing choice: every field op is then a pure elementwise
  DAG over same-shaped vectors with **zero data-movement ops** — no
  stack/concatenate/roll — which XLA fuses into a handful of kernels.
  The previous stacked layout made each multiply materialize its
  (41, N) intermediates through HBM (concatenate/stack are fusion
  breakers), leaving the verify kernel ~25x slower than its ALU cost.
* 13-bit limbs are the sweet spot for int32 lanes: a full schoolbook
  product limb is a sum of 20 partial products each < 2^26, total < 2^31,
  so the whole convolution accumulates in plain int32 with no carries
  inside the inner loop.
* Limbs are kept **nonnegative end-to-end**: subtraction adds a
  per-limb-large multiple of p (``_BIAS``, limbs in [12288, 20479],
  value ≡ 0 mod p) before subtracting, so borrows never ripple and a
  negative carry can never silently fall off the top headroom limb of
  the multiply pipeline. Carry propagation is then monotone and
  converges in a fixed 2-3 rounds (floor-semantics shifts + ``& MASK``).
* Reduction is lazy. ``carry()`` folds the carry-out of limb 19 back into
  limb 0 multiplied by ``WRAP = 2^260 mod p = 608``. Elements stay in a
  redundant range; exact canonical comparisons are done by
  ``canonical()`` / ``is_zero()`` without a full freeze-subtract.
* Everything is static-shaped, static-control-flow jnp code; the hot
  loops live in :mod:`cometbft_tpu.ops.ed25519`. ``stack``/``unstack``
  convert to/from the (20, N) array form at module boundaries (tests,
  the scalar module, byte IO).

Reference seams replaced (behavioral parity targets, not code ports):
the curve25519-voi field element used by the reference's
``crypto/ed25519/ed25519.go`` verify paths.
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 20
LIMB_BITS = 13
MASK = (1 << LIMB_BITS) - 1
P = 2**255 - 19
WRAP = (1 << (NLIMBS * LIMB_BITS)) % P  # 2^260 mod p == 608


# --- compact (rolled) mode ---------------------------------------------
#
# The tuple-of-limbs convolution unrolls to ~1.4k HLO ops per multiply —
# ideal for the TPU backend (pure fusable elementwise DAG) but fatal for
# the XLA *CPU* backend, whose compile time explodes superlinearly on
# the verify kernel's op count (>80 min / OOM at any width and any opt
# level; docs/PERF.md "CPU-backend compile pathology"). Compact mode
# expresses the SAME arithmetic rolled: stacked (nlimbs, N...) arrays, a
# lax.scan over the 20 partial-product rows, and whole-vector carry
# rounds — ~70 HLO ops per multiply, which the CPU backend compiles in
# seconds. Value-identical by construction (same partial products, same
# carry schedule); differential tests cross-check both forms.
#
# Mode selection is per-process: explicitly via set_compact()/env
# GRAFT_COMPACT_FIELD, else automatic — compact exactly on the CPU
# backend (the virtual-mesh dryrun, CPU test lanes, entry()'s CPU
# compile check), tuple form on real accelerators.

_COMPACT = None  # True/False forced, None = auto
_COMPACT_AUTO = None  # cached auto decision


def set_compact(v) -> None:
    """Force compact mode on/off (tests); None restores auto."""
    global _COMPACT
    _COMPACT = v


def compact_mode() -> bool:
    global _COMPACT_AUTO
    if _COMPACT is not None:
        return _COMPACT
    env = os.environ.get("GRAFT_COMPACT_FIELD")
    if env is not None:
        return env == "1"
    if _COMPACT_AUTO is None:
        try:
            import jax

            _COMPACT_AUTO = jax.default_backend() == "cpu"
        except Exception:  # pragma: no cover - uninitializable backend
            _COMPACT_AUTO = False
    return _COMPACT_AUTO


def to_limbs(x: int) -> np.ndarray:
    """Host: python int -> canonical 20-limb int32 vector (value mod p)."""
    return raw_limbs(x % P)


def raw_limbs(x: int) -> np.ndarray:
    """Host: python int -> 20-limb vector WITHOUT reduction (x < 2^260)."""
    assert 0 <= x < 1 << (NLIMBS * LIMB_BITS)
    out = np.zeros(NLIMBS, np.int32)
    for i in range(NLIMBS):
        out[i] = x & MASK
        x >>= LIMB_BITS
    return out


def from_limbs(limbs) -> int:
    """Host/test: one limb vector (any redundancy, signed) -> int mod p."""
    arr = np.asarray(limbs, dtype=np.int64)
    val = 0
    for i in reversed(range(arr.shape[0])):
        val = (val << LIMB_BITS) + int(arr[i])
    return val % P


# --- representation adapters -------------------------------------------


def stack(t):
    """tuple-of-limbs -> one (20, N...) int32 array (module boundary)."""
    shape = jnp.broadcast_shapes(*(jnp.shape(x) for x in t))
    return jnp.stack(
        [jnp.broadcast_to(x, shape).astype(jnp.int32) for x in t], axis=0
    )


def unstack(arr):
    """(20, N...) array -> tuple-of-limbs."""
    return tuple(arr[i] for i in range(NLIMBS))


def unstack_n(arr, n: int):
    """(n, N...) array -> n-tuple (scalar module's variable widths)."""
    return tuple(arr[i] for i in range(n))


def zero(shape=()):
    z = jnp.zeros(shape, jnp.int32)
    return (z,) * NLIMBS


def const(x: int):
    """Device constant: tuple of int32 scalars (broadcasts everywhere)."""
    return tuple(jnp.int32(int(v)) for v in to_limbs(x))


def _bshape(*args):
    return jnp.broadcast_shapes(*(jnp.shape(a[0]) for a in args))


def _carry_stacked(x, rounds: int, wrap: bool):
    """Stacked-array carry rounds (compact mode): x is (n, N...) int32.

    wrap=True folds the top limb's carry into limb 0 times WRAP (the
    20-limb field carry); wrap=False drops it (callers guarantee a zero
    headroom limb, same contract as the tuple _carry_noWrap)."""

    def rnd(x):
        c = lax.shift_right_arithmetic(x, LIMB_BITS)
        r = jnp.bitwise_and(x, MASK)
        up = jnp.concatenate(
            [c[-1:] * WRAP if wrap else jnp.zeros_like(c[-1:]), c[:-1]],
            axis=0,
        )
        return r + up

    if rounds > 4:  # long chains (scalar folds) roll the rounds too
        return lax.fori_loop(0, rounds, lambda _, v: rnd(v), x)
    for _ in range(rounds):
        x = rnd(x)
    return x


def carry(x, rounds: int = 3):
    """Propagate carries; carry-out of limb 19 wraps to limb 0 times WRAP.

    Preserves the value mod p. With inputs bounded by 2^31 the default 3
    rounds bring limbs into (-2^13, 2^13 + WRAP]; pure per-limb
    elementwise ops, the cross-limb shift is just tuple reindexing."""
    if compact_mode():
        return unstack(_carry_stacked(stack(x), rounds, wrap=True))
    for _ in range(rounds):
        c = tuple(lax.shift_right_arithmetic(v, LIMB_BITS) for v in x)
        r = tuple(jnp.bitwise_and(v, MASK) for v in x)
        x = (r[0] + c[NLIMBS - 1] * WRAP,) + tuple(
            r[i] + c[i - 1] for i in range(1, NLIMBS)
        )
    return x


def _make_bias() -> np.ndarray:
    """A multiple of p whose every limb is in [12288, 20479]: added before
    subtraction so limb values stay nonnegative (see module docstring)."""
    base = np.full(NLIMBS, 12288, np.int64)
    v = sum(int(b) << (LIMB_BITS * i) for i, b in enumerate(base)) % P
    adj = to_limbs((-v) % P).astype(np.int64)
    out = base + adj
    assert (out >= 12288).all() and (out <= 20479).all()
    return out.astype(np.int32)


_BIAS = tuple(int(v) for v in _make_bias())


def add(a, b):
    return carry(tuple(x + y for x, y in zip(a, b)), 1)


def sub(a, b):
    """a - b mod p; bias keeps limbs nonneg (inputs must be carried)."""
    return carry(
        tuple(x + k - y for x, y, k in zip(a, b, _BIAS)), 2
    )


def neg(a):
    return carry(tuple(k - x for x, k in zip(a, _BIAS)), 2)


def _conv_mul(a, b):
    """Schoolbook 20x20 limb convolution -> 41-limb tuple.

    Output-stationary: each result limb is an independent sum of <= 20
    lane-wise products — a pure fusable elementwise expression.

    The convolution proper spans limbs 0..38; limbs 39-40 are headroom
    for the carry rounds (limb 38 can carry ~2^13.5 into limb 39, which
    can carry 1 into limb 40 — dropping that bit would lose
    2^520 ≡ WRAP^2)."""
    outs = []
    for k in range(2 * NLIMBS - 1):
        lo = max(0, k - NLIMBS + 1)
        hi = min(NLIMBS - 1, k)
        s = a[lo] * b[k - lo]
        for i in range(lo + 1, hi + 1):
            s = s + a[i] * b[k - i]
        outs.append(s)
    z = jnp.zeros_like(outs[0])
    outs.append(z)  # limb 39 headroom
    outs.append(z)  # limb 40 headroom
    return tuple(outs)


def _carry_noWrap(c, rounds: int = 3):
    n = len(c)
    for _ in range(rounds):
        cc = tuple(lax.shift_right_arithmetic(v, LIMB_BITS) for v in c)
        r = tuple(jnp.bitwise_and(v, MASK) for v in c)
        c = (r[0],) + tuple(r[i] + cc[i - 1] for i in range(1, n))
    return c


def _reduce_41(c):
    """41-limb convolution output -> carried 20-limb element.

    Round counts are tight by bound analysis (checked by the exact
    differential fuzz chains in tests/test_fe25519.py): conv limbs
    < 2^31 -> one noWrap round leaves carries <= 2^18, a second leaves
    limbs <= MASK + 2^5; the fold term hi*WRAP <= 2^22.6, and two wrap
    rounds bring limbs back under MASK + WRAP + 2^5 — the same
    "carried" contract the convolutions assume (products then stay
    under 2^31: carried limbs < 2^13.2, so each of the <= 20 partial
    products is < 2^26.4 and their sum < 2^30.8)."""
    c = _carry_noWrap(c, 2)
    lo = c[:NLIMBS]
    hi = c[NLIMBS : 2 * NLIMBS]
    out = [x + y * WRAP for x, y in zip(lo, hi)]
    out[0] = out[0] + c[2 * NLIMBS] * (WRAP * WRAP)
    return carry(tuple(out), 2)


def _mul_compact(a, b):
    """Compact-mode multiply: the same 20x20 schoolbook convolution as
    _conv_mul/_reduce_41, rolled into a 20-step lax.scan over stacked
    limbs (value-identical partial products and carry schedule, ~20x
    smaller HLO — see the compact-mode note at the top)."""
    A, B = stack(a), stack(b)
    sh = jnp.broadcast_shapes(A.shape[1:], B.shape[1:])

    def _bcast(x):  # align batch dims from the right (scalar consts)
        pad = len(sh) - (x.ndim - 1)
        x = x.reshape((NLIMBS,) + (1,) * pad + x.shape[1:])
        return jnp.broadcast_to(x, (NLIMBS,) + sh).astype(jnp.int32)

    A, B = _bcast(A), _bcast(B)
    acc0 = jnp.zeros((2 * NLIMBS + 1,) + sh, jnp.int32)

    def body(acc, i):
        contrib = lax.dynamic_index_in_dim(A, i, 0, keepdims=False) * B
        seg = lax.dynamic_slice_in_dim(acc, i, NLIMBS, axis=0)
        return (
            lax.dynamic_update_slice_in_dim(acc, seg + contrib, i, axis=0),
            None,
        )

    acc, _ = lax.scan(body, acc0, jnp.arange(NLIMBS))
    # stacked _reduce_41: two no-wrap rounds, fold, two wrap rounds
    acc = _carry_stacked(acc, 2, wrap=False)
    out = acc[:NLIMBS] + acc[NLIMBS : 2 * NLIMBS] * WRAP
    out = out.at[0].add(acc[2 * NLIMBS] * (WRAP * WRAP))
    return unstack(_carry_stacked(out, 2, wrap=True))


def mul(a, b):
    """Field multiply. Inputs must be carried (|limb| <~ 2^13.3)."""
    if compact_mode():
        return _mul_compact(a, b)
    return _reduce_41(_conv_mul(a, b))


def square(a):
    """Field square via the general convolution.

    MEASURED: the symmetric convolution (fewer multiplies: ~110 vs 400)
    is ~30% SLOWER end-to-end on v5e (47.9ms vs 36.6ms @8192 lanes for
    the full verify kernel) — the doubled-cross expression tree
    schedules worse than the regular output-stationary conv, and the
    VPU is not multiply-bound here. Keep the general conv.
    """
    if compact_mode():
        return _mul_compact(a, a)
    return _reduce_41(_conv_mul(a, a))


def mul_scalar(a, k: int):
    """Multiply by a small nonneg python int (k < 2^17)."""
    return carry(tuple(v * jnp.int32(k) for v in a), 3)


def sqn(x, n: int):
    """x^(2^n) via n squarings; fori_loop keeps the HLO small."""
    if n <= 4:
        for _ in range(n):
            x = square(x)
        return x
    return lax.fori_loop(0, n, lambda _, v: square(v), x)


def pow2523(x):
    """x^((p-5)/8) = x^(2^252 - 3). Standard curve25519 addition chain."""
    x2 = square(x)                 # 2
    x4 = square(x2)                # 4
    x8 = square(x4)                # 8
    x9 = mul(x8, x)                # 9
    x11 = mul(x9, x2)              # 11
    x22 = square(x11)              # 22
    x_5_0 = mul(x22, x9)           # 2^5 - 1 = 31
    x_10_5 = sqn(x_5_0, 5)
    x_10_0 = mul(x_10_5, x_5_0)    # 2^10 - 1
    x_20_10 = sqn(x_10_0, 10)
    x_20_0 = mul(x_20_10, x_10_0)  # 2^20 - 1
    x_40_20 = sqn(x_20_0, 20)
    x_40_0 = mul(x_40_20, x_20_0)  # 2^40 - 1
    x_50_10 = sqn(x_40_0, 10)
    x_50_0 = mul(x_50_10, x_10_0)  # 2^50 - 1
    x_100_50 = sqn(x_50_0, 50)
    x_100_0 = mul(x_100_50, x_50_0)    # 2^100 - 1
    x_200_100 = sqn(x_100_0, 100)
    x_200_0 = mul(x_200_100, x_100_0)  # 2^200 - 1
    x_250_50 = sqn(x_200_0, 50)
    x_250_0 = mul(x_250_50, x_50_0)    # 2^250 - 1
    x_252_2 = sqn(x_250_0, 2)
    return mul(x_252_2, x)             # 2^252 - 3


def invert(x):
    """x^(p-2) = x^(2^255 - 21) = (x^(2^252-3))^8 * x^3."""
    t = sqn(pow2523(x), 3)
    return mul(t, mul(square(x), x))


# --- canonicalization / predicates -------------------------------------

_TWO_P = tuple(int(v) for v in raw_limbs(2 * P))
_P_LIMBS = tuple(int(v) for v in raw_limbs(P))


def canonical(x):
    """Return (limbs, ge_p): limbs canonical-nonneg with value in [0, 2p),
    plus a bool mask of lanes whose value is >= p.

    The fully-reduced value is ``limbs - ge_p * p``; parity of the canonical
    value is ``(limbs[0] & 1) ^ ge_p`` (p is odd).
    """
    x = carry(x, 4)              # limbs in (-2^13, 2^13 + WRAP]
    x = tuple(v + t for v, t in zip(x, _TWO_P))
    x = carry(x, 6)              # nonneg carries converge: limbs in [0, 2^13)
    # fold bits 255+ : limb 19 holds bits 247..259
    top = lax.shift_right_arithmetic(x[19], 8)
    x = (
        (x[0] + top * 19,)
        + x[1:19]
        + (jnp.bitwise_and(x[19], 255),)
    )
    x = carry(x, 2)
    # now value < 2^255 + ~600 < 2p, limbs canonical nonneg
    ge = jnp.zeros(_bshape(x), bool)
    eq_above = jnp.ones(_bshape(x), bool)
    for i in reversed(range(NLIMBS)):
        gt = x[i] > _P_LIMBS[i]
        lt = x[i] < _P_LIMBS[i]
        ge = ge | (eq_above & gt)
        eq_above = eq_above & ~gt & ~lt
    ge = ge | eq_above  # x == p counts as >= p
    return x, ge


def is_zero(x):
    """Exact test: value(x) ≡ 0 mod p (vectorized bool, shape = batch)."""
    limbs, _ = canonical(x)
    all_zero = jnp.ones(_bshape(limbs), bool)
    eq_p = jnp.ones(_bshape(limbs), bool)
    for i in range(NLIMBS):
        all_zero = all_zero & (limbs[i] == 0)
        eq_p = eq_p & (limbs[i] == _P_LIMBS[i])
    return all_zero | eq_p


def eq(a, b):
    return is_zero(sub(a, b))


def parity(x):
    """Parity bit of the canonical (fully reduced) value."""
    limbs, ge = canonical(x)
    return jnp.bitwise_xor(
        jnp.bitwise_and(limbs[0], 1), ge.astype(jnp.int32)
    )


# --- byte conversion (device) ------------------------------------------


def from_bytes_255(b):
    """bytes (32, N...) uint8 LE -> (limbs tuple, signbit (N...)).

    Bit 255 split off as the sign. ZIP-215 semantics: y values >= p are
    accepted; the redundant limb form carries the excess, later ops
    reduce mod p.
    """
    b = b.astype(jnp.int32)
    sign = lax.shift_right_arithmetic(b[31], 7)
    rows = [b[i] for i in range(32)]
    rows[31] = jnp.bitwise_and(rows[31], 0x7F)
    return _pack_limbs(rows, NLIMBS), sign


def from_bytes_256(b):
    """bytes (32, N...) uint8 LE -> 20 limbs of the full 256-bit integer."""
    b = b.astype(jnp.int32)
    return _pack_limbs([b[i] for i in range(32)], NLIMBS)


def _pack_limbs(rows, nlimbs: int):
    """rows: list of (N...) int32 byte vectors -> tuple of 13-bit limbs."""
    z = jnp.zeros_like(rows[0])
    rows = rows + [z, z]
    limbs = []
    for i in range(nlimbs):
        bit = LIMB_BITS * i
        byte, off = bit // 8, bit % 8
        v = (
            lax.shift_right_arithmetic(rows[byte], off)
            | (rows[byte + 1] << (8 - off))
            | (rows[byte + 2] << (16 - off))
        )
        limbs.append(jnp.bitwise_and(v, MASK))
    return tuple(limbs)


def select(mask, a, b):
    """Lane select: mask (N...,) bool -> where(mask, a, b) per limb."""
    return tuple(jnp.where(mask, x, y) for x, y in zip(a, b))
