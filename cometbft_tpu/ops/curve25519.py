"""Edwards25519 group operations for TPU, vectorized over batch lanes.

Points are extended homogeneous coordinates ``(X, Y, Z, T)`` — a tuple of
four tuple-of-limbs field elements (see :mod:`cometbft_tpu.ops.fe25519`)
— with x = X/Z, y = Y/Z, x*y = T/Z.

The addition law used ("add-2008-hwcd-3" for a = -1) is **complete** on
edwards25519 (a = -1 is square mod p, d is non-square), so identity and
small-order points need no special casing — crucial for branch-free SIMD
lanes and for ZIP-215 semantics where small/mixed-order points are valid
inputs (reference behavior: curve25519-voi as used by
crypto/ed25519/ed25519.go in the reference repo).

Decompression follows curve25519-dalek / ZIP-215: non-canonical y (>= p)
accepted, x = 0 with sign bit 1 accepted (yields x = 0).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import fe25519 as fe

P = fe.P
_D = (-121665 * pow(121666, P - 2, P)) % P
_SQRT_M1 = pow(2, (P - 1) // 4, P)

# base point y = 4/5
_BY = 4 * pow(5, P - 2, P) % P


def _recover_bx():
    x2 = (_BY * _BY - 1) * pow(_D * _BY * _BY + 1, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P:
        x = x * _SQRT_M1 % P
    if x & 1:
        x = P - x
    return x


_BX = _recover_bx()
BASE_AFFINE = (_BX, _BY)


def identity(shape=()):
    z = jnp.zeros(shape, jnp.int32)
    one = tuple(
        jnp.full(shape, 1, jnp.int32) if i == 0 else z
        for i in range(fe.NLIMBS)
    )
    return ((z,) * fe.NLIMBS, one, one, (z,) * fe.NLIMBS)


def add(p, q):
    """Complete unified addition (add-2008-hwcd-3, a = -1)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = fe.mul(fe.sub(Y1, X1), fe.sub(Y2, X2))
    B = fe.mul(fe.add(Y1, X1), fe.add(Y2, X2))
    C = fe.mul(fe.mul(T1, fe.const(2 * _D % P)), T2)
    ZZ = fe.mul(Z1, Z2)
    Dv = fe.add(ZZ, ZZ)
    E = fe.sub(B, A)
    F = fe.sub(Dv, C)
    G = fe.add(Dv, C)
    H = fe.add(B, A)
    return (fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def double(p, need_t: bool = True):
    """Doubling (dbl-2008-hwcd, a = -1); valid for all points.

    need_t=False skips the T output (one field multiply): T is only
    consumed by the extended ADD formulas, so any double whose result
    feeds another double (or the final identity test) can drop it —
    in the windowed ladder that is 3 of every 4 doubles.
    """
    X1, Y1, Z1, _ = p
    A = fe.square(X1)
    B = fe.square(Y1)
    Zsq = fe.square(Z1)
    C = fe.add(Zsq, Zsq)
    H = fe.add(A, B)
    E = fe.sub(H, fe.square(fe.add(X1, Y1)))
    G = fe.sub(A, B)
    F = fe.add(C, G)
    return (
        fe.mul(E, F),
        fe.mul(G, H),
        fe.mul(F, G),
        fe.mul(E, H) if need_t else None,
    )


def negate(p):
    X, Y, Z, T = p
    return (fe.neg(X), Y, Z, fe.neg(T))


def select(mask, p, q):
    """Lane-wise point select: where(mask, p, q)."""
    return tuple(fe.select(mask, a, b) for a, b in zip(p, q))


def is_identity(p):
    X, Y, Z, _ = p
    return fe.is_zero(X) & fe.is_zero(fe.sub(Y, Z))


def decompress(b):
    """(32, N...) uint8 -> (point, ok). ZIP-215/dalek-liberal decoding.

    Invalid (non-square) lanes return ok=False with the identity point so
    downstream math stays finite.
    """
    y, sign = fe.from_bytes_255(b)
    one = fe.const(1)
    ysq = fe.square(y)
    u = fe.sub(ysq, one)
    v = fe.add(fe.mul(ysq, fe.const(_D)), one)
    # candidate root r = u * v^3 * (u * v^7)^((p-5)/8)
    v3 = fe.mul(fe.square(v), v)
    v7 = fe.mul(fe.square(v3), v)
    r = fe.mul(fe.mul(u, v3), fe.pow2523(fe.mul(u, v7)))
    check = fe.mul(v, fe.square(r))
    root_ok = fe.eq(check, u)
    root_neg = fe.eq(check, fe.neg(u))
    ok = root_ok | root_neg
    x = fe.select(root_neg, fe.mul(r, fe.const(_SQRT_M1)), r)
    # match requested sign (x = 0 stays 0; -0 == 0 under mod p)
    flip = fe.parity(x) != sign
    x = fe.select(flip, fe.neg(x), x)
    shape = jnp.shape(sign)
    one_b = tuple(
        jnp.full(shape, 1, jnp.int32) if i == 0
        else jnp.zeros(shape, jnp.int32)
        for i in range(fe.NLIMBS)
    )
    pt = (x, y, one_b, fe.mul(x, y))
    return select(ok, pt, identity(shape)), ok


def mul_by_cofactor(p):
    """[8]P; the result only feeds is_identity, so no double needs T."""
    return double(
        double(double(p, need_t=False), need_t=False), need_t=False
    )


# --- cached-point forms (windowed ladder) ------------------------------
#
# cached projective: (Y+X, Y-X, Z, 2dT)  — one add costs 8M
# cached affine:     (y+x, y-x, 2dxy), Z == 1 implied — one add costs 7M
# The identity is (1, 1, [1,] 0) in either form, so a d=0 window entry
# needs no special casing (the unified formulas stay complete).


def to_cached(p):
    X, Y, Z, T = p
    return (
        fe.add(Y, X),
        fe.sub(Y, X),
        Z,
        fe.mul(T, fe.const(2 * _D % P)),
    )


def add_cached(p, c):
    """extended p + cached-projective c -> extended (8M)."""
    X1, Y1, Z1, T1 = p
    ypx, ymx, Z2, t2d = c
    A = fe.mul(fe.sub(Y1, X1), ymx)
    B = fe.mul(fe.add(Y1, X1), ypx)
    C = fe.mul(T1, t2d)
    ZZ = fe.mul(Z1, Z2)
    Dv = fe.add(ZZ, ZZ)
    E = fe.sub(B, A)
    F = fe.sub(Dv, C)
    G = fe.add(Dv, C)
    H = fe.add(B, A)
    return (fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def add_affine_cached(p, c, need_t: bool = True):
    """extended p + cached-affine c (Z2 == 1) -> extended (7M; 6M when
    the T output is unused — e.g. the window-final add whose result
    only feeds the next window's doubles)."""
    X1, Y1, Z1, T1 = p
    ypx, ymx, t2d = c
    A = fe.mul(fe.sub(Y1, X1), ymx)
    B = fe.mul(fe.add(Y1, X1), ypx)
    C = fe.mul(T1, t2d)
    Dv = fe.add(Z1, Z1)
    E = fe.sub(B, A)
    F = fe.sub(Dv, C)
    G = fe.add(Dv, C)
    H = fe.add(B, A)
    return (
        fe.mul(E, F),
        fe.mul(G, H),
        fe.mul(F, G),
        fe.mul(E, H) if need_t else None,
    )


def add_projective(p, q):
    """Projective twisted-Edwards addition (add-2008-bbjlp, a = -1):
    needs NO T input on either operand, so it can consume the ladder's
    T-less output for the final R subtraction. Complete for ed25519
    (d non-square). Returns (X, Y, Z, None). ~10M + 1S."""
    X1, Y1, Z1 = p[0], p[1], p[2]
    X2, Y2, Z2 = q[0], q[1], q[2]
    A = fe.mul(Z1, Z2)
    B = fe.square(A)
    C = fe.mul(X1, X2)
    Dv = fe.mul(Y1, Y2)
    E = fe.mul(fe.mul(fe.const(_D), C), Dv)
    F = fe.sub(B, E)
    G = fe.add(B, E)
    X3 = fe.mul(
        fe.mul(A, F),
        fe.sub(
            fe.mul(fe.add(X1, Y1), fe.add(X2, Y2)), fe.add(C, Dv)
        ),
    )
    Y3 = fe.mul(fe.mul(A, G), fe.add(Dv, C))  # D - a*C, a = -1
    Z3 = fe.mul(F, G)
    return (X3, Y3, Z3, None)


def _aff_add(p1, p2):
    """Host-side complete Edwards affine addition (python ints)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    den1 = (1 + _D * x1 * x2 * y1 * y2) % P
    den2 = (1 - _D * x1 * x2 * y1 * y2) % P
    x3 = (x1 * y2 + x2 * y1) * pow(den1, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(den2, P - 2, P) % P
    return (x3, y3)


def affine_window_table(pt):
    """Host: affine-cached table [d]P for d in 0..15 of an affine point
    ``pt = (x, y)`` (python ints), shaped (16, 3, 20) int32. Entry d=0
    is the identity in cached form — the device ladder's window adds
    stay branch-free and complete."""
    import numpy as _np

    out = _np.zeros((16, 3, fe.NLIMBS), _np.int32)
    acc = None  # identity
    for d in range(16):
        if acc is None:
            x, y = 0, 1
        else:
            x, y = acc
        out[d, 0] = fe.to_limbs((y + x) % P)
        out[d, 1] = fe.to_limbs((y - x) % P)
        out[d, 2] = fe.to_limbs(2 * _D * x * y % P)
        acc = _aff_add(acc, pt)
    return out


def base_window_table():
    """Host: affine-cached table [d]B for d in 0..15 — shared by every
    lane of the windowed ladder's fixed-base term."""
    return affine_window_table(BASE_AFFINE)
