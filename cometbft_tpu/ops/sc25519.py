"""Scalar arithmetic mod L = 2^252 + 27742...493 for TPU lanes.

Scalars are plain (non-modular-redundant) little-endian 13-bit limb
**tuples** — one int32 array per limb, batch on the trailing axes (see
the fe25519 layout note: the tuple form keeps every op a fusable
elementwise expression with no concatenate/stack data movement).
Length 20 (260 bits) unless noted. The SHA-512 output reduction
(512 bits -> mod L) uses iterated folding at bit 252:

    X = hi * 2^252 + lo   ==>   X ≡ lo - hi*c  (mod L),  c = L - 2^252.

To keep every intermediate *nonnegative* (so vectorized borrow
propagation converges to canonical limbs for the next bit extraction),
each fold adds a compensating multiple of L:

    X' = lo + (L << s_j) - hi*c   >=  0,     (L << s_j) ≡ 0 (mod L).

Four folds bring 512 bits to < L + 2^252 < 2L; one conditional subtract
finishes. All shapes and loops are static for XLA.

Replaces the reference's big-int `mod L` in ed25519 verification
(crypto/ed25519 + curve25519-voi scalar arithmetic).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from . import fe25519 as fe
from .fe25519 import LIMB_BITS, MASK, NLIMBS

L = 2**252 + 27742317777372353535851937790883648493
_C = L - 2**252  # 125 bits


def _raw(x: int, n: int) -> np.ndarray:
    assert 0 <= x < 1 << (n * LIMB_BITS)
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= LIMB_BITS
    return out


_C_LIMBS = tuple(int(v) for v in _raw(_C, 10))
_L_LIMBS = tuple(int(v) for v in _raw(L, 20))


def from_limbs(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    val = 0
    for i in reversed(range(arr.shape[0])):
        val = (val << LIMB_BITS) + int(arr[i])
    return val


def carry_plain(x, rounds=None):
    """Carry/borrow propagation, no modular wraparound (carry-out of the
    top limb must be impossible by construction — keep a headroom limb).
    Works for signed limbs provided the represented *value* is
    nonnegative and rounds >= nlimbs + 6 when borrows may ripple."""
    x = tuple(x)
    n = len(x)
    if rounds is None:
        rounds = n + 6
    if fe.compact_mode():
        # rolled form for the CPU backend (see fe25519 compact note);
        # identical round-by-round schedule, top carry dropped the same
        return fe.unstack_n(
            fe._carry_stacked(fe.stack(x), rounds, wrap=False), n
        )
    for _ in range(rounds):
        c = tuple(lax.shift_right_arithmetic(v, LIMB_BITS) for v in x)
        r = tuple(jnp.bitwise_and(v, MASK) for v in x)
        x = (r[0],) + tuple(r[i] + c[i - 1] for i in range(1, n))
    return x


def _conv(a, b_const) -> tuple:
    """Full product limbs(a) x constant limbs -> len(a)+len(b) limbs.

    Output-stationary (see fe25519._conv_mul): each limb an independent
    fusable sum of products by int constants."""
    a = tuple(a)
    na, nb = len(a), len(b_const)
    outs = []
    for k in range(na + nb - 1):
        lo = max(0, k - nb + 1)
        hi = min(na - 1, k)
        s = a[lo] * jnp.int32(b_const[k - lo])
        for i in range(lo + 1, hi + 1):
            s = s + a[i] * jnp.int32(b_const[k - i])
        outs.append(s)
    outs.append(jnp.zeros_like(outs[0]))
    return tuple(outs)


def _split_252(x):
    """x: canonical nonneg limb tuple -> (lo = x mod 2^252 as 20 limbs,
    hi = x >> 252 with n-19 limbs)."""
    x = tuple(x)
    n = len(x)
    lo = x[:19] + (jnp.bitwise_and(x[19], 31),)
    z = jnp.zeros_like(x[0])
    xp = x + (z,)
    hi = tuple(
        jnp.bitwise_and(
            lax.shift_right_arithmetic(xp[i], 5)
            | (jnp.bitwise_and(xp[i + 1], 31) << 8),
            MASK,
        )
        for i in range(19, n)
    )
    return lo, hi


def _ge_limbs(a, b_const) -> jnp.ndarray:
    """Lexicographic a >= b for canonical nonneg limb vectors."""
    a = tuple(a)
    shape = jnp.broadcast_shapes(*(jnp.shape(v) for v in a))
    ge = jnp.zeros(shape, bool)
    eq_above = jnp.ones(shape, bool)
    for i in reversed(range(len(a))):
        b = b_const[i] if i < len(b_const) else 0
        gt = a[i] > b
        lt = a[i] < b
        ge = ge | (eq_above & gt)
        eq_above = eq_above & ~gt & ~lt
    return ge | eq_above


def _fold_once(x, shift: int):
    """One fold: canonical nonneg x -> x' ≡ x (mod L), carried canonical."""
    lo, hi = _split_252(x)
    hic = _conv(hi, _C_LIMBS)
    k = L << shift
    nk = (k.bit_length() + LIMB_BITS - 1) // LIMB_BITS + 1
    n = max(len(lo), len(hic), nk) + 1
    kl = tuple(int(v) for v in _raw(k, n))
    z = jnp.zeros_like(lo[0])

    def at(t, i):
        return t[i] if i < len(t) else z

    out = tuple(at(lo, i) + kl[i] - at(hic, i) for i in range(n))
    return carry_plain(out)


def reduce_512(x40):
    """40-limb tuple of a 512-bit LE integer -> canonical scalar mod L,
    20-limb tuple in [0, L)."""
    x = carry_plain(x40)
    x = _fold_once(x, 134)   # < 2^388
    x = _fold_once(x, 10)    # < 2^263
    x = _fold_once(x, 0)     # < L + 2^252 < 2L
    x = _fold_once(x, 0)     # safety margin, keeps < 2L
    x = tuple(x)[:NLIMBS]
    ge = _ge_limbs(x, _L_LIMBS)
    x = tuple(
        jnp.where(ge, v - jnp.int32(b), v)
        for v, b in zip(x, _L_LIMBS)
    )
    return carry_plain(x)


def neg_mod_L(h):
    """L - h for canonical h in [0, L). h = 0 maps to L (a 253-bit value),
    harmless in cofactored verification: [8][L]A = identity for any A."""
    return carry_plain(
        tuple(jnp.int32(b) - v for v, b in zip(tuple(h), _L_LIMBS))
    )


def lt_L(s):
    """Canonicity check s < L for canonical nonneg 20-limb scalars."""
    return ~_ge_limbs(s, _L_LIMBS)


def bits(s, n: int = 253):
    """Limb tuple -> (n, N...) bit planes, little-endian bit order
    (leading axis = bit index, ready for fori_loop dynamic indexing)."""
    s = tuple(s)
    planes = []
    for j in range(n):
        limb, off = divmod(j, LIMB_BITS)
        planes.append(
            jnp.bitwise_and(lax.shift_right_arithmetic(s[limb], off), 1)
        )
    return jnp.stack(planes, axis=0)


def digits4(s, nwin: int = 64):
    """Canonical limb tuple -> (nwin, N...) 4-bit windows, little-endian
    window order (window j = bits 4j..4j+3). Stacked output: the ladder
    dynamic-indexes one window per fori_loop step."""
    s = tuple(s)
    sp = s + (jnp.zeros_like(s[0]),)
    outs = []
    for j in range(nwin):
        limb, off = divmod(4 * j, LIMB_BITS)
        v = lax.shift_right_arithmetic(sp[limb], off)
        if off > LIMB_BITS - 4:
            v = v | (sp[limb + 1] << (LIMB_BITS - off))
        outs.append(jnp.bitwise_and(v, 15))
    return jnp.stack(outs, axis=0)


def hash_bytes_to_limbs(b):
    """(64, N...) uint8 digest bytes (LE integer) -> 40-limb tuple."""
    b = b.astype(jnp.int32)
    rows = [b[i] for i in range(64)]
    z = jnp.zeros_like(rows[0])
    rows += [z, z]
    limbs = []
    for i in range(40):
        bit = LIMB_BITS * i
        byte, off = bit // 8, bit % 8
        v = (
            lax.shift_right_arithmetic(rows[byte], off)
            | (rows[byte + 1] << (8 - off))
            | (rows[byte + 2] << (16 - off))
        )
        limbs.append(jnp.bitwise_and(v, MASK))
    return tuple(limbs)
