"""Scalar arithmetic mod L = 2^252 + 27742...493 for TPU lanes.

Scalars are plain (non-modular-redundant) little-endian 13-bit limb
vectors in int32, **limb axis first** (shape ``(nlimbs, N...)``), length
20 (260 bits) unless noted. The SHA-512 output reduction (512 bits ->
mod L) uses iterated folding at bit 252:

    X = hi * 2^252 + lo   ==>   X ≡ lo - hi*c  (mod L),  c = L - 2^252.

To keep every intermediate *nonnegative* (so vectorized borrow
propagation converges to canonical limbs for the next bit extraction),
each fold adds a compensating multiple of L:

    X' = lo + (L << s_j) - hi*c   >=  0,     (L << s_j) ≡ 0 (mod L).

Four folds bring 512 bits to < L + 2^252 < 2L; one conditional subtract
finishes. All shapes and loops are static for XLA.

Replaces the reference's big-int `mod L` in ed25519 verification
(crypto/ed25519 + curve25519-voi scalar arithmetic).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from .fe25519 import LIMB_BITS, MASK, NLIMBS

L = 2**252 + 27742317777372353535851937790883648493
_C = L - 2**252  # 125 bits


def _raw(x: int, n: int) -> np.ndarray:
    assert 0 <= x < 1 << (n * LIMB_BITS)
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= LIMB_BITS
    return out


_C_LIMBS = _raw(_C, 10)
_L_LIMBS = _raw(L, 20)


def _cst(arr: np.ndarray, ndim: int):
    return jnp.asarray(arr).reshape(arr.shape + (1,) * (ndim - 1))


def from_limbs(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    val = 0
    for i in reversed(range(arr.shape[0])):
        val = (val << LIMB_BITS) + int(arr[i])
    return val


def carry_plain(x, rounds=None):
    """Carry/borrow propagation, no modular wraparound (carry-out of the
    top limb must be impossible by construction — keep a headroom limb).
    Works for signed limbs provided the represented *value* is
    nonnegative and rounds >= nlimbs + 6 when borrows may ripple."""
    if rounds is None:
        rounds = x.shape[0] + 6
    for _ in range(rounds):
        c = lax.shift_right_arithmetic(x, LIMB_BITS)
        r = jnp.bitwise_and(x, MASK)
        x = r + jnp.concatenate(
            [jnp.zeros_like(c[-1:]), c[:-1]], axis=0
        )
    return x


def _conv(a, b_const: np.ndarray):
    """Full product limbs(a) x constant limbs -> len(a)+len(b) limbs.

    Output-stationary (see fe25519._conv_mul): each limb an independent
    fusable sum, no scatter-add accumulator."""
    na, nb = a.shape[0], b_const.shape[0]
    bc = _cst(b_const, a.ndim)
    outs = []
    for k in range(na + nb - 1):
        lo = max(0, k - nb + 1)
        hi = min(na - 1, k)
        s = a[lo] * bc[k - lo]
        for i in range(lo + 1, hi + 1):
            s = s + a[i] * bc[k - i]
        outs.append(s)
    outs.append(jnp.zeros_like(outs[0]))
    return jnp.stack(outs, axis=0)


def _split_252(x):
    """x: canonical nonneg limbs (n, N...) -> (lo = x mod 2^252 as 20
    limbs, hi = x >> 252 with n-19 limbs)."""
    n = x.shape[0]
    lo = x[:NLIMBS].at[19].set(jnp.bitwise_and(x[19], 31))
    pad = jnp.zeros((1,) + x.shape[1:], jnp.int32)
    xp = jnp.concatenate([x, pad], axis=0)
    hi = jnp.bitwise_and(
        lax.shift_right_arithmetic(xp[19:n], 5)
        | (jnp.bitwise_and(xp[20 : n + 1], 31) << 8),
        MASK,
    )
    return lo, hi


def _ge_limbs(a, b_const: np.ndarray):
    """Lexicographic a >= b for canonical nonneg limb vectors."""
    bc = _cst(b_const, a.ndim)
    gt = a > bc
    lt = a < bc
    ge = jnp.zeros(a.shape[1:], bool)
    eq_above = jnp.ones(a.shape[1:], bool)
    for i in reversed(range(a.shape[0])):
        ge = ge | (eq_above & gt[i])
        eq_above = eq_above & ~gt[i] & ~lt[i]
    return ge | eq_above


def _fold_once(x, shift: int):
    """One fold: canonical nonneg x -> x' ≡ x (mod L), carried canonical."""
    lo, hi = _split_252(x)
    hic = _conv(hi, _C_LIMBS)
    k = L << shift
    nk = (k.bit_length() + LIMB_BITS - 1) // LIMB_BITS + 1
    n = max(lo.shape[0], hic.shape[0], nk) + 1
    kl = _cst(_raw(k, n), x.ndim)

    def pad(v):
        return jnp.concatenate(
            [v, jnp.zeros((n - v.shape[0],) + v.shape[1:], jnp.int32)],
            axis=0,
        )

    out = pad(lo) + kl - pad(hic)
    return carry_plain(out)


def reduce_512(x40):
    """(40, N...) limbs of a 512-bit LE integer -> canonical scalar mod L,
    (20, N...) limbs in [0, L)."""
    x = carry_plain(x40)
    x = _fold_once(x, 134)   # < 2^388
    x = _fold_once(x, 10)    # < 2^263
    x = _fold_once(x, 0)     # < L + 2^252 < 2L
    x = _fold_once(x, 0)     # safety margin, keeps < 2L
    x = x[:NLIMBS]
    ge = _ge_limbs(x, _L_LIMBS)
    x = jnp.where(ge[None], x - _cst(_L_LIMBS, x.ndim), x)
    return carry_plain(x)


def neg_mod_L(h):
    """L - h for canonical h in [0, L). h = 0 maps to L (a 253-bit value),
    harmless in cofactored verification: [8][L]A = identity for any A."""
    return carry_plain(_cst(_L_LIMBS, h.ndim) - h)


def lt_L(s):
    """Canonicity check s < L for canonical nonneg 20-limb scalars."""
    return ~_ge_limbs(s, _L_LIMBS)


def bits(s, n: int = 253):
    """(20, N...) limbs -> (n, N...) bit planes, little-endian bit order
    (leading axis = bit index, ready for fori_loop dynamic indexing)."""
    planes = []
    for j in range(n):
        limb, off = divmod(j, LIMB_BITS)
        planes.append(
            jnp.bitwise_and(lax.shift_right_arithmetic(s[limb], off), 1)
        )
    return jnp.stack(planes, axis=0)


def digits4(s, nwin: int = 64):
    """(20, N...) canonical limbs -> (nwin, N...) 4-bit windows,
    little-endian window order (window j = bits 4j..4j+3). Feeds the
    windowed double-scalar ladder."""
    pad = jnp.zeros((1,) + s.shape[1:], jnp.int32)
    sp = jnp.concatenate([s, pad], axis=0)
    outs = []
    for j in range(nwin):
        limb, off = divmod(4 * j, LIMB_BITS)
        v = lax.shift_right_arithmetic(sp[limb], off)
        if off > LIMB_BITS - 4:
            v = v | (sp[limb + 1] << (LIMB_BITS - off))
        outs.append(jnp.bitwise_and(v, 15))
    return jnp.stack(outs, axis=0)


def hash_bytes_to_limbs(b):
    """(64, N...) uint8 digest bytes (LE integer) -> (40, N...) limbs."""
    b = b.astype(jnp.int32)
    pad = jnp.zeros((2,) + b.shape[1:], jnp.int32)
    b = jnp.concatenate([b, pad], axis=0)
    limbs = []
    for i in range(40):
        bit = LIMB_BITS * i
        byte, off = bit // 8, bit % 8
        v = (
            lax.shift_right_arithmetic(b[byte], off)
            | (b[byte + 1] << (8 - off))
            | (b[byte + 2] << (16 - off))
        )
        limbs.append(jnp.bitwise_and(v, MASK))
    return jnp.stack(limbs, axis=0)
