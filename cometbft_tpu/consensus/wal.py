"""Consensus WAL: fsync'd, size-capped rotating log of consensus inputs.

Parity with reference consensus/wal.go + libs/autofile/group.go: CRC32
+ length framing (wal.go:295), EndHeightMessage markers (:41),
WriteSync fsync barrier (:202), SearchForEndHeight (:232, cross-file),
corruption-tolerant replay, and **file rotation** — the head file
rotates once it exceeds ``head_size_limit`` (group.go:65 headSizeLimit,
RotateFile :265) and the oldest rotated files are deleted when the
group exceeds ``total_size_limit`` (group.go checkTotalSizeLimit), so a
node at height 10k does not carry an unbounded WAL.

Layout: the head is ``<path>``; rotated files are ``<path>.000``,
``<path>.001``, ... (monotonically increasing). Readers iterate the
group in index order then the head; records never span files (rotation
happens between records).

Record: [crc32(payload) u32 BE][len u32 BE][payload]; payload is a
proto-encoded TimedWALMessage.

Group commit (docs/PERF.md "Live consensus fast path"): with
``group_commit_ms > 0``, ``write_group`` appends the record
immediately but defers the fsync to a flusher thread that coalesces
every barrier enqueued within the window into ONE fsync — the
autofile file-group design's batching seam, made explicit. Callers
get a :class:`SyncTicket` that completes only after the covering
fsync; durability stays prefix-ordered (an fsync covers every record
appended before it), so "ticket done" is exactly as strong as the
serial ``write_sync`` barrier. ``group_commit_ms == 0`` keeps the
strict serial path (write_group degenerates to write_sync).

Routing is measurement-driven (the crypto dispatch calibration's
philosophy applied to disk): coalescing only pays when the fsync is
genuinely expensive — on an NVMe with a volatile write cache a
barrier costs ~0.1 ms and the cross-thread ticket handoff costs
more, while on a sync-through datacenter disk the barrier costs
milliseconds and coalescing collapses 3-4 of them per height into
one. ``write_group`` therefore tracks an EWMA of observed fsync
walls and routes strict-inline below ``fsync_slow_s`` (never a
regression on fast disks), engaging the group seam above it. Tests
force the seam with ``fsync_slow_s=0``; ``set_fsync_model`` injects
a synthetic barrier cost so the bench/chaos can model slow disks on
fast hardware.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional, Tuple

from ..analysis.runtime import sanitized_lock
from ..trace import NOOP as TRACE_NOOP
from ..utils import proto
from ..utils.fail import fail_point
from ..utils.log import get_logger

_log = get_logger("wal")

MAX_MSG_SIZE = 2 * 1024 * 1024

# reference autofile defaults: 10 MB head, 1 GB group total
DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024
DEFAULT_TOTAL_SIZE_LIMIT = 1024 * 1024 * 1024

# message kinds
MSG_EVENT = 1        # internal state-machine event (round step string)
MSG_PROPOSAL = 2
MSG_BLOCK_PART = 3
MSG_VOTE = 4
MSG_TIMEOUT = 5
MSG_END_HEIGHT = 6


@dataclass
class WALMessage:
    kind: int
    height: int = 0
    round: int = 0
    step: str = ""
    data: bytes = b""
    peer_id: str = ""
    time_ns: int = 0

    def encode(self) -> bytes:
        return (
            proto.field_varint(1, self.kind)
            + proto.field_varint(2, self.height)
            + proto.field_varint(3, self.round)
            + proto.field_string(4, self.step)
            + proto.field_bytes(5, self.data)
            + proto.field_string(6, self.peer_id)
            + proto.field_varint(7, self.time_ns)
        )

    @classmethod
    def decode(cls, b: bytes) -> "WALMessage":
        m = proto.parse(b)
        return cls(
            kind=proto.get1(m, 1, 0),
            height=proto.get1(m, 2, 0),
            round=proto.get1(m, 3, 0),
            step=proto.get1(m, 4, b"").decode(),
            data=proto.get1(m, 5, b""),
            peer_id=proto.get1(m, 6, b"").decode(),
            time_ns=proto.get1(m, 7, 0),
        )


class SyncTicket:
    """Completion handle for one group-committed sync barrier.

    Done exactly when an fsync covering the ticket's record has
    returned. A crash (``crash_close``) leaves undone tickets undone
    forever — the record was never acked, so the caller's deferred
    externalization (vote/proposal broadcast) never fires, which is
    precisely the no-acked-then-lost crash contract."""

    __slots__ = ("_ev", "_cbs", "_lock")

    def __init__(self, done: bool = False):
        self._ev = threading.Event()
        self._cbs: List[Callable] = []
        self._lock = threading.Lock()
        if done:
            self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._ev.wait(timeout)

    def add_done_callback(self, fn: Callable) -> None:
        """fn() after the covering fsync; runs on the flusher thread
        (or inline when already done) — marshal to your loop yourself."""
        with self._lock:
            if not self._ev.is_set():
                self._cbs.append(fn)
                return
        fn()

    def _complete(self) -> None:
        with self._lock:
            self._ev.set()
            cbs, self._cbs = self._cbs, []
        for fn in cbs:
            try:
                fn()
            except Exception:
                import traceback

                traceback.print_exc()


# shared pre-completed ticket for the strict (window = 0) path
_DONE_TICKET = SyncTicket(done=True)

# synthetic per-fsync barrier cost (seconds) for slow-disk modeling:
# bench ablations and chaos nemeses set this to measure the group
# seam's effect on hardware whose own fsync is too fast to show it
# (NVMe + volatile write cache ~0.1 ms vs the 1-10 ms of sync-through
# production disks). 0.0 = real disk only.
_FSYNC_MODEL_S = 0.0

# below this measured fsync wall, coalescing cannot win: the ticket
# handoff (flusher wakeup + loop marshal) costs more than the barrier
# it batches. ~0.5 ms sits between cached-NVMe and sync-through media.
DEFAULT_FSYNC_SLOW_S = 0.0005


def set_fsync_model(delay_s: float) -> None:
    """Install a synthetic slow-disk barrier cost (bench/chaos only)."""
    global _FSYNC_MODEL_S
    _FSYNC_MODEL_S = max(0.0, delay_s)

_ROT_RE = re.compile(r"\.(\d{3,})$")


def _group_files(path: str) -> List[str]:
    """All files of the group in read order: rotated (by index) + head."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path)
    rotated = []
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        names = []
    for name in names:
        if not name.startswith(base + "."):
            continue
        m = _ROT_RE.search(name[len(base):])
        if m:
            rotated.append((int(m.group(1)), os.path.join(d, name)))
    out = [p for _, p in sorted(rotated)]
    if os.path.exists(path):
        out.append(path)
    return out


def prune_group_below(path: str, height: int) -> Tuple[int, int]:
    """Delete sealed (rotated) WAL files whose every record is below
    ``height``; returns (files_deleted, bytes_freed).

    The retention plane's WAL leg (store/retention.py): replay after
    a restart never needs records below the retained end-height, so a
    rotated file whose max recorded height is < height is dead
    weight. The HEAD file is never deleted (it is open for append),
    and an unreadable/empty rotated file is left alone — pruning must
    never turn a corrupt-but-diagnosable group into a gap. Deletion
    goes oldest-first and stops at the first file that must stay, so
    the group never ends up with a hole in its rotation order."""
    freed_files = freed_bytes = 0
    for p in _group_files(path):
        if p == path:
            break  # never the head
        max_h = None
        for msg in WAL._iter_file(p):
            if msg.height > (max_h or 0):
                max_h = msg.height
        if max_h is None or max_h >= height:
            break  # unreadable or still-needed: stop, keep the rest
        try:
            sz = os.path.getsize(p)
            os.remove(p)
        except OSError:
            break
        freed_files += 1
        freed_bytes += sz
    return freed_files, freed_bytes


class WAL:
    def __init__(
        self,
        path: str,
        head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
        total_size_limit: int = DEFAULT_TOTAL_SIZE_LIMIT,
        tracer=None,
        group_commit_ms: float = 0.0,
        fsync_slow_s: float = DEFAULT_FSYNC_SLOW_S,
    ):
        self.path = path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self.tracer = tracer or TRACE_NOOP
        # group-commit window: barriers enqueued within it share one
        # fsync (0 = strict serial write_sync path)
        self.group_commit_ms = group_commit_ms
        # calibrated engage threshold: strict-inline while the fsync
        # EWMA sits below this (fast disk — deferral would only add
        # latency); 0 forces the group seam unconditionally (tests)
        self.fsync_slow_s = fsync_slow_s
        self._fsync_ewma_s: Optional[float] = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        self._head_size = self._f.tell()
        # one RLock over every file mutation: the consensus loop
        # appends, the group flusher fsyncs, and a pipelined-finalize
        # worker may write_end_height concurrently (sanitized:
        # the lock-order graph watches it, docs/LINT.md)
        self._lock = sanitized_lock(threading.RLock(), "wal.append")
        self._pending: List[SyncTicket] = []
        self._flush_wakeup = threading.Condition(self._lock)
        self._flusher: Optional[threading.Thread] = None
        self._closed = False
        # observability: coalescing ratio = group_coalesced/group_fsyncs
        self.group_fsyncs = 0
        self.group_coalesced = 0

    def write(self, msg: WALMessage) -> None:
        if not msg.time_ns:
            msg.time_ns = time.time_ns()
        payload = msg.encode()
        if len(payload) > MAX_MSG_SIZE:
            raise ValueError("WAL message too big")
        rec = struct.pack(
            ">II", zlib.crc32(payload) & 0xFFFFFFFF, len(payload)
        )
        with self._lock:
            self._f.write(rec + payload)
            self._head_size += 8 + len(payload)
            if self._head_size >= self.head_size_limit:
                self._rotate()

    def write_sync(self, msg: WALMessage) -> None:
        """The fsync barrier (own votes/proposals + end-height markers
        MUST hit disk before acting; reference consensus/wal.go:202).
        The append takes the lock; the fsync (inside flush_sync) runs
        WITHOUT it, so concurrent appends — the consensus loop, while
        a pipelined finalize writes its end-height marker on a worker
        — never park behind the disk."""
        self.write(msg)
        self.flush_sync()

    def write_group(self, msg: WALMessage) -> SyncTicket:
        """Group-committed sync barrier: append now, fsync within
        ``group_commit_ms``. The returned ticket completes once a
        covering fsync lands (possibly a strict flush_sync issued by
        another caller — durability is prefix-ordered). Degenerates
        to write_sync (done ticket) when the window is 0 OR the
        calibrated router says the disk is fast (fsync EWMA below
        ``fsync_slow_s`` — coalescing would only add handoff
        latency there)."""
        if self.group_commit_ms <= 0 or (
            self.fsync_slow_s > 0
            and (
                self._fsync_ewma_s is None
                or self._fsync_ewma_s < self.fsync_slow_s
            )
        ):
            # fast disk (or still measuring): the strict barrier IS
            # the cheaper path — do it inline and keep the EWMA warm
            self.write_sync(msg)
            return _DONE_TICKET
        with self._lock:
            if self._closed:
                raise ValueError("WAL is closed")
            self.write(msg)
            ticket = SyncTicket()
            self._pending.append(ticket)
            if self._flusher is None:
                self._flusher = threading.Thread(
                    target=self._flusher_loop,
                    name="wal-group-commit",
                    daemon=True,
                )
                self._flusher.start()
            self._flush_wakeup.notify()
        return ticket

    def _flusher_loop(self) -> None:
        """One fsync per window for however many barriers queued up —
        the bounded-barrier guarantee: a ticket waits at most
        ~group_commit_ms + one fsync."""
        window_s = self.group_commit_ms / 1000.0
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._flush_wakeup.wait()
                if self._closed:
                    # graceful close fsyncs + completes leftovers
                    # itself; crash_close abandons them (power cut)
                    return
            # coalesce OUTSIDE the lock so barriers keep enqueueing
            time.sleep(window_s)
            with self._lock:
                if self._closed:
                    return
                do_flush = bool(self._pending)
            if do_flush:
                # flush_sync takes the lock only for the buffer
                # flush + fd dup; the fsync itself runs lock-free
                try:
                    self.flush_sync()
                except (OSError, ValueError):
                    with self._lock:
                        if self._closed:
                            # fd yanked mid-crash_close: tickets stay
                            # undone, exactly like the power cut this
                            # models
                            return
                    # transient disk error: flush_sync re-queued the
                    # tickets; keep the flusher alive and retry next
                    # window (a dead flusher would silently stop
                    # every future broadcast behind the FIFO)
                    _log.error(
                        "WAL group fsync failed; retrying next window",
                        path=self.path,
                    )

    def flush_sync(self) -> None:
        # the fsync barrier is the consensus hot path's only disk
        # stall — span it so step latencies attribute to it. ANY
        # fsync completes every pending group ticket: their records
        # were appended+flushed before this fsync started (same
        # lock), and fsync durability covers the whole file prefix.
        #
        # The fsync itself runs OUTSIDE the append lock, on a dup'd
        # fd: holding the lock across the disk stall would park the
        # consensus loop behind the flusher thread on every WAL
        # append (measured 10x liveness loss at small windows), and
        # the dup keeps the fd valid across a concurrent rotation.
        with self._lock:
            tickets, self._pending = self._pending, []
            try:
                self._f.flush()
                fd = os.dup(self._f.fileno())
            except (OSError, ValueError):
                # nothing durable happened: the tickets go back to
                # the FRONT of the queue, still unacked
                self._pending = tickets + self._pending
                raise
        name = "wal.fsync.group" if tickets else "wal.fsync"
        t0 = time.perf_counter()
        try:
            with self.tracer.span(name, tid="wal", n=len(tickets) or 1):
                # the WAL seam is the ONE sanctioned blocking sink
                # (cf. ASY111): strict-inline routing is calibrated
                # (EWMA, sub-ms fsyncs only), the group path runs on
                # the off-loop flusher, and rotation's in-lock
                # barrier is required by the rename-atomicity +
                # ticket-prefix-durability contract
                os.fsync(fd)  # bftlint: disable=ASY114 — the one sanctioned WAL blocking seam (strict-inline calibrated, group path off-loop)
                if _FSYNC_MODEL_S > 0:
                    # synthetic slow-disk model for bench/chaos legs
                    time.sleep(_FSYNC_MODEL_S)  # bftlint: disable=ASY114 — synthetic slow-disk model, bench/chaos legs only
        except OSError:
            with self._lock:
                self._pending = tickets + self._pending
            raise
        finally:
            os.close(fd)
        wall = time.perf_counter() - t0
        # EWMA of the barrier cost drives the strict-vs-group routing
        prev = self._fsync_ewma_s
        self._fsync_ewma_s = (
            wall if prev is None else prev + 0.3 * (wall - prev)
        )
        if tickets:
            self.group_fsyncs += 1
            self.group_coalesced += len(tickets)
        for t in tickets:
            t._complete()

    def write_end_height(self, height: int) -> None:
        self.write_sync(WALMessage(kind=MSG_END_HEIGHT, height=height))

    def close(self) -> None:
        flusher = self._stop_flusher()
        if flusher is not None:
            flusher.join(timeout=5.0)
        try:
            self.flush_sync()
        except Exception:
            pass
        with self._lock:
            self._f.close()

    def _stop_flusher(self) -> Optional[threading.Thread]:
        with self._lock:
            self._closed = True
            self._flush_wakeup.notify_all()
            return self._flusher

    def crash_close(self) -> None:
        """Power-cut close (chaos harness): release the file WITHOUT
        flushing Python's userspace buffer — records written since the
        last fsync barrier are lost, exactly like a real crash. The fd
        is redirected to /dev/null first so the buffered tail drains
        harmlessly instead of reaching the WAL on GC. Pending group
        tickets are NEVER completed: an unacked barrier must stay
        unacked across the cut."""
        self._stop_flusher()  # no join: a crash doesn't wait for anyone
        with self._lock:
            try:
                devnull = os.open(os.devnull, os.O_WRONLY)
                try:
                    os.dup2(devnull, self._f.fileno())
                finally:
                    os.close(devnull)
            except OSError:
                pass
            self._f.close()

    # --- rotation -----------------------------------------------------

    def _next_index(self) -> int:
        top = -1
        for p in _group_files(self.path):
            m = _ROT_RE.search(p)
            if m:
                top = max(top, int(m.group(1)))
        return top + 1

    def _rotate(self) -> None:
        """Head -> <path>.<index>; fresh head. Records never span files.

        Crash-safety: the head is flushed+fsync'd before the rename, the
        rename is atomic, and a crash at any point leaves a readable
        group (a missing head is recreated on reopen). Matches
        libs/autofile/group.go:265 RotateFile.
        """
        self.flush_sync()
        self._f.close()
        idx = self._next_index()
        fail_point("wal-rotate-before-rename")
        os.replace(self.path, f"{self.path}.{idx:03d}")
        fail_point("wal-rotate-after-rename")
        self._f = open(self.path, "ab")
        self._head_size = 0
        _log.debug("rotated WAL head", path=self.path, index=idx)
        self._enforce_total_limit()

    def _enforce_total_limit(self) -> None:
        """Delete oldest rotated files while the group exceeds the total
        cap (group.go checkTotalSizeLimit — the head never deletes)."""
        files = _group_files(self.path)
        sizes = {p: os.path.getsize(p) for p in files if os.path.exists(p)}
        total = sum(sizes.values())
        for p in files:
            if total <= self.total_size_limit or p == self.path:
                break
            try:
                os.remove(p)
                total -= sizes.get(p, 0)
                _log.info(
                    "WAL group over size cap, removed oldest file",
                    file=p,
                )
            except OSError:
                break

    # --- reading ------------------------------------------------------

    @staticmethod
    def _iter_file(path: str, stats: Optional[dict] = None):
        """Yield valid records; on stop, ``stats`` (if given) gets
        ``valid_bytes`` (length of the valid record prefix) and
        ``size`` (file size) — a single pass answers both "what are the
        records" and "is there trailing garbage"."""
        if not os.path.exists(path):
            if stats is not None:
                stats["valid_bytes"] = stats["size"] = 0
            return
        pos = 0
        with open(path, "rb") as f:
            try:
                while True:
                    hdr = f.read(8)
                    if len(hdr) < 8:
                        return
                    crc, ln = struct.unpack(">II", hdr)
                    if ln > MAX_MSG_SIZE:
                        return
                    payload = f.read(ln)
                    if len(payload) < ln:
                        return
                    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                        return
                    try:
                        msg = WALMessage.decode(payload)
                    except Exception:
                        return
                    yield msg
                    pos += 8 + ln
            finally:
                if stats is not None:
                    stats["valid_bytes"] = pos
                    try:
                        stats["size"] = os.path.getsize(path)
                    except OSError:
                        stats["size"] = pos

    @classmethod
    def iter_messages(cls, path: str) -> Iterator[WALMessage]:
        """Yields messages across the whole group (rotated files in
        index order, then the head). A corrupt record inside any file
        stops iteration entirely — everything after it is suspect, the
        same stop-at-first-bad-record semantic as the reference."""
        for p in _group_files(path):
            stats: dict = {}
            yield from cls._iter_file(p, stats)
            if p != path and stats.get("size", 0) > stats.get(
                "valid_bytes", 0
            ):
                # a rotated (sealed) file that ends mid-record was cut
                # by corruption, not by an in-progress write: stop
                return

    @classmethod
    def search_for_end_height(
        cls, path: str, height: int
    ) -> Optional[int]:
        """Global message index right after ENDHEIGHT(height), or None."""
        for i, msg in enumerate(cls.iter_messages(path)):
            if msg.kind == MSG_END_HEIGHT and msg.height == height:
                return i + 1
        return None

    @classmethod
    def messages_after_end_height(cls, path: str, height: int):
        found = False
        for msg in cls.iter_messages(path):
            if found:
                yield msg
            elif msg.kind == MSG_END_HEIGHT and msg.height == height:
                found = True

    @classmethod
    def repair_torn_tail(cls, path: str) -> int:
        """Truncate a torn tail off the HEAD file in place; returns
        the bytes removed (0 when the head is clean or absent).

        A power cut can leave a partial record at the head's end (a
        real torn write, or the chaos harness's ``wal_torn_tail``
        injection). Replay tolerates it — iteration stops at the
        first bad record — but the WAL reopens in append mode, so
        WITHOUT this repair every record written after the garbage
        would be unreadable on the NEXT restart: silent amnesia one
        crash later. The valid prefix is already in place, so this
        is one ``truncate`` + fsync, not a rewrite (rotated files
        are sealed behind an fsync barrier and cannot tear; cross-
        file corruption repair stays with truncate_corrupt_tail)."""
        if not os.path.exists(path):
            return 0
        stats: dict = {}
        for _ in cls._iter_file(path, stats):
            pass
        torn = stats.get("size", 0) - stats.get("valid_bytes", 0)
        if torn <= 0:
            return 0
        with open(path, "r+b") as f:
            f.truncate(stats["valid_bytes"])
            f.flush()
            os.fsync(f.fileno())
        _log.info(
            "repaired torn WAL tail",
            path=path,
            removed_bytes=torn,
            kept_bytes=stats["valid_bytes"],
        )
        return torn

    @classmethod
    def truncate_corrupt_tail(cls, path: str) -> int:
        """Repair: keep only the valid record prefix of the group.

        The file containing the first corrupt record is rewritten to its
        valid prefix and every later file is deleted; earlier files are
        untouched (no multi-GB rewrite). Returns the total number of
        valid messages in the group (reference WAL repair,
        consensus/state.go:2677).
        """
        files = _group_files(path)
        total = 0
        for fi, p in enumerate(files):
            stats: dict = {}
            msgs = list(cls._iter_file(p, stats))
            total += len(msgs)
            if stats.get("size", 0) > stats.get("valid_bytes", 0):
                tmp = p + ".repair"
                if os.path.exists(tmp):
                    # stale temp from a crashed earlier repair: a fresh
                    # repair must not append after its partial contents
                    os.remove(tmp)
                w = WAL(tmp, head_size_limit=1 << 62)
                for m in msgs:
                    w.write(m)
                w.close()
                os.replace(tmp, p)
                for later in files[fi + 1 :]:
                    if later != p:
                        try:
                            os.remove(later)
                        except OSError:
                            pass
                # a deleted head must be recreated so the group stays
                # writable / iterable from <path>
                if p != path:
                    open(path, "ab").close()
                break
        return total
