"""Consensus WAL: fsync'd append-only log of every consensus input.

Parity with reference consensus/wal.go: CRC32 + length framing (:295),
EndHeightMessage markers (:41), WriteSync fsync barrier (:202),
SearchForEndHeight (:232), and corruption-tolerant replay (decode stops
at the first bad record, reference repair path consensus/state.go:2677).

Record: [crc32(payload) u32 BE][len u32 BE][payload]; payload is a
proto-encoded TimedWALMessage.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

from ..utils import codec, proto

MAX_MSG_SIZE = 2 * 1024 * 1024

# message kinds
MSG_EVENT = 1        # internal state-machine event (round step string)
MSG_PROPOSAL = 2
MSG_BLOCK_PART = 3
MSG_VOTE = 4
MSG_TIMEOUT = 5
MSG_END_HEIGHT = 6


@dataclass
class WALMessage:
    kind: int
    height: int = 0
    round: int = 0
    step: str = ""
    data: bytes = b""
    peer_id: str = ""
    time_ns: int = 0

    def encode(self) -> bytes:
        return (
            proto.field_varint(1, self.kind)
            + proto.field_varint(2, self.height)
            + proto.field_varint(3, self.round)
            + proto.field_string(4, self.step)
            + proto.field_bytes(5, self.data)
            + proto.field_string(6, self.peer_id)
            + proto.field_varint(7, self.time_ns)
        )

    @classmethod
    def decode(cls, b: bytes) -> "WALMessage":
        m = proto.parse(b)
        return cls(
            kind=proto.get1(m, 1, 0),
            height=proto.get1(m, 2, 0),
            round=proto.get1(m, 3, 0),
            step=proto.get1(m, 4, b"").decode(),
            data=proto.get1(m, 5, b""),
            peer_id=proto.get1(m, 6, b"").decode(),
            time_ns=proto.get1(m, 7, 0),
        )


class WAL:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    def write(self, msg: WALMessage) -> None:
        if not msg.time_ns:
            msg.time_ns = time.time_ns()
        payload = msg.encode()
        if len(payload) > MAX_MSG_SIZE:
            raise ValueError("WAL message too big")
        rec = struct.pack(
            ">II", zlib.crc32(payload) & 0xFFFFFFFF, len(payload)
        )
        self._f.write(rec + payload)

    def write_sync(self, msg: WALMessage) -> None:
        """The fsync barrier (own votes/proposals + end-height markers
        MUST hit disk before acting; reference consensus/wal.go:202)."""
        self.write(msg)
        self.flush_sync()

    def flush_sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def write_end_height(self, height: int) -> None:
        self.write_sync(WALMessage(kind=MSG_END_HEIGHT, height=height))

    def close(self) -> None:
        try:
            self.flush_sync()
        except Exception:
            pass
        self._f.close()

    # --- reading ------------------------------------------------------

    @staticmethod
    def iter_messages(path: str) -> Iterator[WALMessage]:
        """Yields messages until EOF or the first corrupt record."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    return
                crc, ln = struct.unpack(">II", hdr)
                if ln > MAX_MSG_SIZE:
                    return
                payload = f.read(ln)
                if len(payload) < ln:
                    return
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    return
                try:
                    yield WALMessage.decode(payload)
                except Exception:
                    return

    @classmethod
    def search_for_end_height(
        cls, path: str, height: int
    ) -> Optional[int]:
        """Message index right after ENDHEIGHT(height), or None."""
        for i, msg in enumerate(cls.iter_messages(path)):
            if msg.kind == MSG_END_HEIGHT and msg.height == height:
                return i + 1
        return None

    @classmethod
    def messages_after_end_height(cls, path: str, height: int):
        found = False
        for msg in cls.iter_messages(path):
            if found:
                yield msg
            elif msg.kind == MSG_END_HEIGHT and msg.height == height:
                found = True

    @classmethod
    def truncate_corrupt_tail(cls, path: str) -> int:
        """Repair: rewrite the WAL keeping only valid records; returns
        number of valid messages (reference WAL repair)."""
        msgs = list(cls.iter_messages(path))
        tmp = path + ".repair"
        w = WAL(tmp)
        for m in msgs:
            w.write(m)
        w.close()
        os.replace(tmp, path)
        return len(msgs)
