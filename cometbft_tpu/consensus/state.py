"""The BFT consensus state machine (reference consensus/state.go).

Tendermint rounds: propose -> prevote -> precommit -> commit, with
locking/unlocking, POL (proof-of-lock) tracking, WAL-before-act
persistence and crash replay.

Architecture (TPU-host-native, not a goroutine port): one asyncio task
(`_receive_routine`) is the single writer over RoundState — peers,
internal messages and timeouts all arrive on one queue, mirroring the
reference's single-threaded receiveRoutine (consensus/state.go:789)
without its mutex web. Timeouts are asyncio timers that enqueue; the
block executor + TPU signature verification run inline (they are the
actual work); gossip runs in reactor tasks reading RoundState snapshots.
"""

from __future__ import annotations

import asyncio
import time
import traceback
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .. import types as T
from ..config import ConsensusConfig
from ..state.state_types import State
from ..trace import NOOP as TRACE_NOOP
from ..types import events as ev
from ..utils import codec
from ..utils.fail import fail_point
from ..utils.tasks import spawn
from ..utils.log import Lazy, get_logger
from . import wal as walmod
from .types import HeightVoteSet, RoundState, Step

_log = get_logger("consensus")


@dataclass
class ProposalMessage:
    proposal: T.Proposal


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: T.Part


@dataclass
class VoteMessage:
    vote: T.Vote


@dataclass
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: Step


class ConsensusState:
    def __init__(
        self,
        config: ConsensusConfig,
        state: State,
        block_exec,
        block_store,
        mempool,
        priv_validator=None,
        event_bus: Optional[ev.EventBus] = None,
        wal_path: Optional[str] = None,
        evidence_pool=None,
        on_decided: Optional[Callable] = None,
    ):
        self.config = config
        # loop-affinity guard (analysis/runtime.py): consensus
        # state is mutated only on its event loop
        from ..analysis.runtime import get_sanitizer

        self._sanitizer = get_sanitizer()
        self.block_exec = block_exec
        self.block_store = block_store
        self.mempool = mempool
        self.privval = priv_validator
        self.event_bus = event_bus or ev.EventBus()
        self.evpool = evidence_pool
        self.on_decided = on_decided  # hook: (height, block_id, block)

        # shared with the reactor's async coalescing verifier: votes
        # pre-verified in batches resolve as cache hits in add_vote
        self.sig_cache = T.SignatureCache()
        self.rs = RoundState()
        self.state: Optional[State] = None
        self.queue: "asyncio.Queue" = None  # created in start()
        self._timeout_task: Optional[asyncio.Task] = None
        self._routine_task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event() if False else None
        self.wal: Optional[walmod.WAL] = None
        self._wal_path = wal_path
        self._broadcast_hooks: List[Callable] = []
        self.decided_heights = 0
        # tracing plane (trace/, docs/TRACE.md): the node build swaps
        # in the real per-node tracer; NOOP keeps call sites
        # unconditional. Step spans are opened/closed across
        # callsites, so the open handles live here (LIFO:
        # height ⊇ round ⊇ step — Perfetto nests them by time range).
        self.tracer = TRACE_NOOP
        self._sp_height = None
        self._sp_round = None
        self._sp_step = None
        # commit-latency attribution (ISSUE 7, docs/TRACE.md
        # "Cross-node timelines"): per-height monotonic marks the
        # quorum spans and the last-commit breakdown are computed
        # from. All reset by update_to_state.
        self._round_t0_ns = 0
        self._proposal_complete_ns = 0
        self._verify_ns = 0
        self._quorum_at: Dict = {}  # (round, "prevote"|"precommit") -> ns
        self._vote_first: Dict = {}  # (round, vote type) -> first-arrival ns
        # {"height", "phases": {...}, "dominant"} for the last height
        # this node committed — served by RPC health so a degraded
        # verdict can cite the dominant phase
        self.last_commit_breakdown: Optional[Dict] = None
        # --- live-consensus fast path (docs/PERF.md) -----------------
        # in-round vote micro-batcher (built in start(): needs a loop);
        # peer votes pre-verify in coalesced batches and resolve as
        # sig_cache hits in add_vote
        self._vote_coalescer = None
        # pipelined finalize: height currently persisting/applying
        # off-loop (None = none; at most ONE in flight by design) and
        # the next-height messages parked until that height opens
        self._finalize_inflight: Optional[int] = None
        self._finalize_task: Optional[asyncio.Task] = None
        self._parked: List[Tuple] = []
        # deferred externalizations awaiting their WAL barrier, in
        # submission order (see _after_durable)
        self._durable_fifo: List[Tuple] = []

        self.update_to_state(state)

    # --- wiring -------------------------------------------------------

    def add_broadcast_hook(self, fn: Callable) -> None:
        """fn(kind, payload): called for every message this node emits
        (proposal / block part / vote) — the reactor's gossip feed."""
        self._broadcast_hooks.append(fn)

    def _broadcast(self, kind: str, payload) -> None:
        for fn in self._broadcast_hooks:
            try:
                fn(kind, payload)
            except Exception:
                traceback.print_exc()

    # --- lifecycle ----------------------------------------------------

    async def start(self) -> None:
        from ..obs.queues import InstrumentedQueue

        self._sanitizer.tag("consensus.state")

        self.queue = InstrumentedQueue(10000, name="consensus.inbox")
        self.event_bus.set_loop(asyncio.get_running_loop())
        if self._wal_path:
            # a power cut may have left a torn partial record at the
            # head's end; repair BEFORE reopening for append, or every
            # record written this incarnation lands after the garbage
            # and is lost on the next restart (wal.repair_torn_tail)
            walmod.WAL.repair_torn_tail(self._wal_path)
            self.wal = walmod.WAL(
                self._wal_path,
                tracer=self.tracer,
                group_commit_ms=self.config.wal_group_commit_ms,
            )
            self._catchup_replay()
            self._reconcile_privval_state()
        if self.config.vote_batch_window_ms > 0:
            # in-round vote-verify micro-batching (the blocksync
            # pre-verify pattern applied to live rounds): one batch
            # dispatch per arrival window, results land in sig_cache
            from ..crypto.coalesce import CoalescingVerifier

            self._vote_coalescer = CoalescingVerifier(
                cache=self.sig_cache,
                window_s=self.config.vote_batch_window_ms / 1000.0,
            )
        self._routine_task = asyncio.create_task(self._receive_routine())
        # kick off the first height
        self._schedule_timeout(
            0.0, self.rs.height, 0, Step.NEW_HEIGHT
        )

    async def stop(self) -> None:
        await self._halt(graceful=True)

    async def crash(self) -> None:
        """Abrupt in-process stop (chaos harness): cancel the routines
        and abandon the WAL without flushing buffered records — the
        power-cut analog of stop(). Recovery must come exclusively
        from fsync'd WAL prefixes + persisted stores."""
        await self._halt(graceful=False)

    async def _halt(self, graceful: bool) -> None:
        if self._routine_task:
            self._routine_task.cancel()
            try:
                # bounded (ASY110): a receive routine wedged in a
                # swallowed cancel must not hang the halt — the WAL
                # close below seals the durable state either way
                await asyncio.wait_for(self._routine_task, 10.0)
            except asyncio.TimeoutError:
                _log.error(
                    "receive routine ignored cancel past budget, "
                    "abandoning", height=self.rs.height,
                )
            except asyncio.CancelledError:
                if not self._routine_task.cancelled():
                    raise  # outer cancel of stop()/crash(): propagate
            except Exception:
                traceback.print_exc()
        if self._timeout_task:
            self._timeout_task.cancel()
        if self._finalize_task and not self._finalize_task.done():
            if graceful:
                try:
                    # bounded (ASY110): let an in-flight finalize land
                    # before sealing the WAL; a wedged apply is
                    # abandoned (recovery replays from the stores)
                    await asyncio.wait_for(self._finalize_task, 10.0)
                except asyncio.TimeoutError:
                    pass
                except asyncio.CancelledError:
                    if not self._finalize_task.cancelled():
                        raise  # outer cancel of stop(): propagate
                except Exception:
                    traceback.print_exc()
            else:
                self._finalize_task.cancel()
        if self._vote_coalescer is not None and graceful:
            try:
                # flush the last vote window so no future leaks into a
                # dead loop (drops are fine — the machine is stopping)
                await asyncio.wait_for(self._vote_coalescer.drain(), 5.0)
            except asyncio.TimeoutError:
                pass
            except asyncio.CancelledError:
                raise
            except Exception:
                traceback.print_exc()
        if self.wal:
            if graceful:
                self.wal.close()
            else:
                self.wal.crash_close()
        # record the in-progress height's open spans: the ring must
        # show what this node was doing when it stopped/crashed —
        # that partial timeline is exactly what the chaos dump
        # exists for
        self._close_trace_spans()

    # --- state transitions --------------------------------------------

    def update_to_state(self, state: State) -> None:
        """Reset RoundState for the next height (reference updateToState)."""
        if (
            self.rs.commit_round > -1
            and 0 < self.rs.height <= state.last_block_height
        ):
            pass  # committed by us; moving on
        self.state = state
        height = state.last_block_height + 1
        if height == state.initial_height:
            last_precommits = None
        else:
            last_precommits = self.rs.votes.precommits(
                self.rs.commit_round
            ) if self.rs.votes and self.rs.commit_round >= 0 else None
        # fresh height: reset the commit-latency attribution marks
        self._round_t0_ns = time.monotonic_ns()
        self._proposal_complete_ns = 0
        self._verify_ns = 0
        self._quorum_at = {}
        self._vote_first = {}
        self.rs = RoundState(
            height=height,
            round=0,
            step=Step.NEW_HEIGHT,
            validators=state.validators.copy(),
            votes=HeightVoteSet(
                state.chain_id, height, state.validators,
                sig_cache=self.sig_cache,
            ),
            last_commit=last_precommits,
            last_validators=state.last_validators.copy()
            if state.last_validators and getattr(state.last_validators, "validators", None)
            else None,
            start_time_ns=time.time_ns(),
        )

    # --- receive routine (single writer) ------------------------------

    async def _receive_routine(self) -> None:
        while True:
            item = await self.queue.get()
            try:
                kind, payload, peer_id = item
                if kind == "timeout":
                    self._wal_write(
                        walmod.WALMessage(
                            kind=walmod.MSG_TIMEOUT,
                            height=payload.height,
                            round=payload.round,
                            step=str(int(payload.step)),
                        ),
                        sync=True,
                    )
                    self._handle_timeout(payload)
                else:
                    if self._park_next_height(kind, payload, peer_id):
                        continue
                    if (
                        kind == "vote"
                        and peer_id != ""
                        and self._maybe_prestage_vote(payload, peer_id)
                    ):
                        continue  # re-enqueues once batch-verified
                    self._wal_write_msg(kind, payload, peer_id)
                    self._handle_msg(kind, payload, peer_id)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                _log.error(
                    "receive routine error",
                    height=self.rs.height,
                    kind=item[0] if item else "?",
                    err=repr(e),
                )
                traceback.print_exc()
            if (
                self._vote_coalescer is not None
                and self.queue.qsize() == 0
            ):
                # inbox drained = the natural micro-batch boundary:
                # dispatch the staged vote wave NOW instead of letting
                # the window timer starve behind a busy loop
                self._vote_coalescer.flush()

    def _handle_msg(self, kind: str, payload, peer_id: str) -> None:
        if self._sanitizer.enabled:
            self._sanitizer.touch("consensus.state")
        if self.tracer.enabled:
            self._trace_handle(kind, payload, peer_id)
        if kind == "proposal":
            if self._set_proposal(payload.proposal) and peer_id != "":
                self._broadcast("proposal", payload)
        elif kind == "block_part":
            added = self._add_proposal_block_part(
                payload.height, payload.round, payload.part
            )
            if added and peer_id != "":
                self._broadcast("block_part", payload)
        elif kind == "vote":
            self._try_add_vote(payload.vote, peer_id)
        elif kind == "commit_block":
            self._handle_commit_block(payload, peer_id)
        elif kind == "retry_sign":
            self._handle_sign_retry(payload)
        elif kind == "signed_vote":
            # our own vote, signed off-loop by a remote signer
            self._commit_own_vote(payload.vote)
        elif kind == "signed_proposal":
            prop, parts = payload
            self._publish_own_proposal(prop, parts)

    def _trace_handle(self, kind: str, payload, peer_id: str) -> None:
        """Correlated handling instant (ISSUE 7): the state-machine
        side of the p2p.msg.recv instants — the gap between the two is
        the consensus-inbox queue wait."""
        h = r = None
        if kind == "proposal":
            h, r = payload.proposal.height, payload.proposal.round
        elif kind == "block_part":
            h, r = payload.height, payload.round
        elif kind in ("vote", "signed_vote"):
            h, r = payload.vote.height, payload.vote.round
        elif kind == "commit_block":
            h = payload.block.height
        else:
            return
        self.tracer.instant(
            "consensus.msg.handle", tid="consensus", kind=kind,
            h=h, r=r, peer=peer_id[:12] if peer_id else "self",
        )

    def _handle_commit_block(self, payload, peer_id: str) -> None:
        """Catch-up: a peer sent us a committed block + its commit
        (the reactor-level analog of the reference's part-by-part
        catch-up gossip, consensus/reactor.go gossipDataForCatchup).
        Verify the commit against OUR validator set, then ingest."""
        rs = self.rs
        block, commit = payload.block, payload.commit
        if block.height != rs.height:
            return
        # reuse peer wire bytes only when they produce the PSH the
        # commit binds to — a non-canonical encoding of a valid block
        # must fall back to canonical re-encode, not get dropped
        # (same guard as blocksync/reactor.py's apply loop)
        raw = getattr(block, "_raw_bytes", None)
        parts = None
        if raw is not None:
            parts = T.PartSet.from_data(raw)
            if parts.header.hash != commit.block_id.part_set_header.hash:
                parts = None
        if parts is None:
            parts = T.PartSet.from_data(codec.encode_block(block))
        bid = T.BlockID(block.hash(), parts.header)
        if commit.block_id.hash != bid.hash:
            return
        if rs.step >= Step.COMMIT:
            # already committing from our own precommits, but we may be
            # MISSING the block itself (enter_commit's "reset parts"
            # branch). Adopt the received block if it matches the
            # committed BlockID, then finalize.
            maj = (
                rs.votes.precommits(rs.commit_round).two_thirds_majority()
                if rs.commit_round >= 0
                else None
            )
            if (
                rs.proposal_block is None
                and maj is not None
                and not maj.is_nil()
                and maj.hash == bid.hash
                and maj.part_set_header.hash == parts.header.hash
            ):
                rs.proposal_block = block
                rs.proposal_block_parts = parts
                self._try_finalize_commit(block.height)
            return
        try:
            T.verify_commit(
                self.state.chain_id,
                rs.validators,
                bid,
                block.height,
                commit,
                cache=self.sig_cache,
                priority=T.PRIORITY_LIVE,
            )
        except Exception:
            return
        self.ingest_verified_block(block, parts, commit)
        # persist the EC the sender shipped alongside (reference
        # SaveBlockWithExtendedCommit on every commit path): without
        # this, a node that caught up here can never serve the EC to a
        # future blocksync joiner. Invalid/missing EC never rejects the
        # block — the plain commit already verified.
        ec_bytes = getattr(payload, "ec_bytes", None)
        if ec_bytes and self.state.consensus_params.vote_extensions_enabled(
            block.height
        ):
            if not self.block_store.load_extended_commit(block.height):
                try:
                    # the EC's embedded commit carries the same
                    # precommit sigs just verified above: with the
                    # shared cache the re-check is near-free and only
                    # the extension lanes cost real verifies
                    T.verify_extended_commit(
                        self.state.chain_id,
                        rs.validators,
                        bid.hash,
                        block.height,
                        codec.decode_extended_commit(ec_bytes),
                        cache=self.sig_cache,
                        priority=T.PRIORITY_LIVE,
                    )
                    self.block_store.save_extended_commit(
                        block.height, ec_bytes
                    )
                except Exception:
                    traceback.print_exc()

    def ingest_verified_block(self, block, parts, commit):
        """Adaptive-sync ingest (reference consensus/state_ingest.go:231
        + reactor IngestVerifiedBlock): commit a block WITHOUT running
        rounds. Caller must have verified `commit` against this
        height's validator set. Returns the post-apply State."""
        rs = self.rs
        if block.height != rs.height:
            raise ValueError(
                f"ingest at height {block.height}, consensus at {rs.height}"
            )
        if rs.step >= Step.COMMIT:
            raise ValueError("consensus already committing this height")
        bid = T.BlockID(block.hash(), parts.header)
        return self._apply_committed_block(
            block, parts, commit, bid, immediate=True
        )

    def _apply_committed_block(
        self, block, parts, commit, bid, immediate: bool
    ):
        """Shared tail of _finalize_commit and ingest_verified_block:
        persist, WAL-barrier, apply, advance to the next height."""
        timings = self._finalize_tail(block, parts, commit, bid)
        return self._complete_finalize(
            block, bid, timings, immediate=immediate
        )

    def _finalize_tail(self, block, parts, commit, bid) -> Tuple:
        """The blocking legs: persist -> WAL end-height barrier ->
        ABCI apply (strictly this order; reference state.go:1769-1837)."""
        t_fin, t_persist, t_wal = self._finalize_persist(
            block, parts, commit
        )
        return self._finalize_apply(
            block, bid, t_fin, t_persist, t_wal
        )

    def _finalize_persist(self, block, parts, commit) -> Tuple:
        """Persist + WAL end-height barrier — the GIL-releasing disk
        legs (sqlite writes, fsync). Thread-safe against the receive
        loop (stores and the WAL take their own locks), so the
        pipelined path overlaps them with live gossip relay via
        asyncio.to_thread. The pure-Python ABCI apply deliberately
        does NOT ride along: offloading it to a thread just fights
        the loop for the GIL and loses outright on a 2-vCPU host."""
        height = block.height
        t_fin = time.monotonic_ns()
        fail_point("cs-before-save-block")  # reference state.go:1769
        if self.block_store.height() < height:
            self.block_store.save_block(block, parts, commit)
        else:
            self.block_store.save_seen_commit(height, commit)
        t_persist = time.monotonic_ns()
        fail_point("cs-after-save-block")  # :1786
        if self.wal:
            self.wal.write_end_height(height)
        t_wal = time.monotonic_ns()
        fail_point("cs-after-wal-end-height")  # :1809
        return t_fin, t_persist, t_wal

    def _finalize_apply(
        self, block, bid, t_fin, t_persist, t_wal
    ) -> Tuple:
        new_state = self.block_exec.apply_verified_block(
            self.state, bid, block
        )
        t_apply = time.monotonic_ns()
        fail_point("cs-after-apply")  # :1837
        return new_state, t_fin, t_persist, t_wal, t_apply

    def _complete_finalize(
        self, block, bid, timings, immediate: bool,
        pipelined: bool = False,
    ):
        """Loop-side completion: record the waterfall, advance to the
        next height, release parked next-height messages."""
        new_state, t_fin, t_persist, t_wal, t_apply = timings
        height = block.height
        _log.info(
            "finalized block",
            height=height,
            hash=Lazy(lambda: block.hash()[:8].hex()),
            txs=len(block.data.txs),
            app_hash=Lazy(lambda: new_state.app_hash[:8].hex()),
        )
        self.decided_heights += 1
        # finalize leg of the commit waterfall (recorded before the
        # height span closes below, so it nests in Perfetto)
        self.tracer.complete(
            "consensus.finalize", t_fin, t_apply - t_fin,
            tid="consensus", height=height,
            persist_ms=round((t_persist - t_fin) / 1e6, 3),
            wal_ms=round((t_wal - t_persist) / 1e6, 3),
            apply_ms=round((t_apply - t_wal) / 1e6, 3),
            pipelined=pipelined,
        )
        if pipelined:
            # end-to-end pipelined finalize including the loop handoff
            # (its own budget entry; the loop itself never stalled)
            self.tracer.complete(
                "consensus.finalize.pipelined", t_fin,
                time.monotonic_ns() - t_fin,
                tid="consensus", height=height,
            )
        self._note_commit_breakdown(height, t_fin, t_persist, t_wal, t_apply)
        # close the height's span stack and stamp the commit;
        # ingest-path commits may have no open round/step spans
        self._close_trace_spans()
        self.tracer.instant(
            "consensus.commit", tid="consensus",
            height=height, txs=len(block.data.txs),
        )
        if self.on_decided:
            try:
                self.on_decided(height, bid, block)
            except Exception:
                traceback.print_exc()
        self.update_to_state(new_state)
        if pipelined:
            self._finalize_inflight = None
            self._finalize_task = None
        if self._parked and self.queue is not None:
            # the new height just opened: replay everything that
            # arrived for it early, ahead of whatever else is queued
            parked, self._parked = self._parked, []
            for item in parked:
                try:
                    self.queue.put_nowait(item)
                except asyncio.QueueFull:
                    # inbox drowning (10k deep): shed THIS item and
                    # keep trying the rest — the standard overload
                    # policy; dropping the whole tail would lose a
                    # proposal the flood never resends
                    self.queue.count_drop()
        if self.queue is not None:  # only once started
            self._schedule_timeout(
                0.0
                if immediate or self.config.skip_timeout_commit
                else self.config.timeout_commit_s,
                self.rs.height,
                0,
                Step.NEW_HEIGHT,
            )
        return new_state

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        rs = self.rs
        if ti.height != rs.height or (
            ti.round < rs.round
            or (ti.round == rs.round and ti.step < rs.step and ti.step != Step.NEW_HEIGHT)
        ):
            return
        if ti.step == Step.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == Step.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == Step.PROPOSE:
            self.event_bus.publish_type(ev.EVENT_TIMEOUT_PROPOSE, rs.height)
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == Step.PREVOTE_WAIT:
            self.event_bus.publish_type(ev.EVENT_TIMEOUT_WAIT, rs.height)
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == Step.PRECOMMIT_WAIT:
            self.event_bus.publish_type(ev.EVENT_TIMEOUT_WAIT, rs.height)
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)

    # --- WAL ----------------------------------------------------------

    def _wal_write_msg(
        self, kind: str, payload, peer_id: str
    ) -> Optional[walmod.SyncTicket]:
        if self.wal is None:
            return None
        if kind == "proposal":
            m = walmod.WALMessage(
                kind=walmod.MSG_PROPOSAL,
                height=payload.proposal.height,
                round=payload.proposal.round,
                data=codec.encode_proposal(payload.proposal),
                peer_id=peer_id,
            )
        elif kind == "block_part":
            from ..store.block_store import _encode_part

            m = walmod.WALMessage(
                kind=walmod.MSG_BLOCK_PART,
                height=payload.height,
                round=payload.round,
                data=_encode_part(payload.part),
                peer_id=peer_id,
            )
        elif kind == "vote":
            m = walmod.WALMessage(
                kind=walmod.MSG_VOTE,
                height=payload.vote.height,
                round=payload.vote.round,
                data=codec.encode_vote(payload.vote),
                peer_id=peer_id,
            )
        else:
            return None
        # own messages (peer_id == "") are fsync barriers (state.go:881)
        return self._wal_write(m, sync=(peer_id == ""))

    def _wal_write(
        self, m: walmod.WALMessage, sync: bool
    ) -> Optional[walmod.SyncTicket]:
        """Returns the barrier's SyncTicket for sync writes (done
        immediately on the strict path, after the covering group
        fsync otherwise); None for async writes / no WAL."""
        if self.wal is None:
            return None
        if sync:
            # group seam: with wal_group_commit_ms == 0 this IS the
            # strict write_sync and the ticket comes back done
            return self.wal.write_group(m)
        self.wal.write(m)
        return None

    def _after_durable(self, ticket, fn: Callable) -> None:
        """WAL-before-act: run ``fn`` (an externalization — broadcast
        of our own vote/proposal) only once its barrier record is
        durable. Strict-path / absent tickets run inline.

        Deferred actions drain through a FIFO, NOT straight off each
        ticket: a later barrier whose ticket happens to be done at
        registration time (its group fsync landed while the current
        handler was still running) must not jump ahead of an earlier
        barrier whose callback is still queued on the loop — peers
        receiving a proposer's first block part before its proposal
        drop the part on the floor, and flood delivery never resends
        (observed as systematic round-0 failure)."""
        if ticket is None or (ticket.done() and not self._durable_fifo):
            fn()
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (sync test harness): the barrier cannot be
            # awaited — block for it, bounded, then act
            ticket.wait(1.0)
            fn()
            return
        self._durable_fifo.append((ticket, fn))
        ticket.add_done_callback(
            lambda: loop.call_soon_threadsafe(self._drain_durable)
        )

    def _drain_durable(self) -> None:
        """Run every queued externalization whose barrier has landed,
        strictly in submission order (head-of-line blocks the rest)."""
        fifo = self._durable_fifo
        while fifo and fifo[0][0].done():
            _, fn = fifo.pop(0)
            try:
                fn()
            except Exception:
                traceback.print_exc()

    # --- in-round vote pre-verification (fast path) -------------------

    def _maybe_prestage_vote(self, payload, peer_id: str) -> bool:
        """Route a current-height peer vote through the coalescing
        batch verifier; returns True when staged (the vote re-enqueues
        once its batch resolves, then lands in add_vote as a
        sig_cache hit). Anything the batcher cannot judge — other
        heights, unknown indexes, already-cached signatures — handles
        inline, where add_vote produces the canonical verdicts."""
        vc = self._vote_coalescer
        if vc is None:
            return False
        vote = payload.vote
        rs = self.rs
        if (
            vote.height != rs.height
            or rs.validators is None
            or not vote.signature
            or not 0 <= vote.validator_index < rs.validators.size()
        ):
            return False
        val = rs.validators.get_by_index(vote.validator_index)
        if val is None or val.address != vote.validator_address:
            return False
        sb = vote.sign_bytes(self.state.chain_id)
        if self.sig_cache.contains(
            sb, vote.signature, val.pub_key.key_bytes
        ):
            # pre-verified (reactor batch, a re-delivery, or our own
            # earlier window): inline handling cache-hits — also the
            # cycle-breaker for the re-enqueued staged vote itself
            return False
        fut = vc.submit(val.pub_key, sb, vote.signature)

        def _done(f: "asyncio.Future") -> None:
            ok = False
            try:
                ok = bool(f.result())
            except Exception:
                pass
            if not ok:
                _log.error(
                    "dropping vote with invalid signature",
                    height=vote.height,
                    round=vote.round,
                    peer=peer_id[:12],
                )
                return
            # same-loop continuation: the callback runs on the event
            # loop, i.e. inside the single-writer context — handle
            # directly instead of paying another queue round trip and
            # a second prestage/cache pass. The height may have moved
            # while the batch was in flight: the normal park/height
            # guards apply.
            try:
                if self._park_next_height("vote", payload, peer_id):
                    return
                self._wal_write_msg("vote", payload, peer_id)
                self._handle_msg("vote", payload, peer_id)
            except Exception:
                traceback.print_exc()

        fut.add_done_callback(_done)
        return True

    # --- pipelined-finalize parking -----------------------------------

    _PARK_LIMIT = 2048

    def _park_next_height(self, kind: str, payload, peer_id: str) -> bool:
        """Messages for the NEXT height would be dropped by the height
        guards and cost a gossip-retransmit round trip (or, on a
        flood-only harness, the whole round). They arrive whenever
        delivery is not globally ordered — a peer that committed
        first proposes h+1 while our own commit of h is a few ms from
        landing (batched vote windows, group-commit broadcast
        deferral, pipelined finalize). Park them (bounded) and replay
        at height entry."""
        h = None
        if kind == "proposal":
            h = payload.proposal.height
        elif kind == "block_part":
            h = payload.height
        elif kind in ("vote", "signed_vote"):
            h = payload.vote.height
        elif kind == "commit_block":
            h = payload.block.height
        if h is None or h != self.rs.height + 1:
            return False
        if len(self._parked) < self._PARK_LIMIT:
            self._parked.append((kind, payload, peer_id))
        return True

    def _catchup_replay(self) -> None:
        """Replay WAL messages for the current height after a crash
        (reference consensus/replay.go:94)."""
        path = self._wal_path
        end_prev = walmod.WAL.search_for_end_height(
            path, self.rs.height - 1
        )
        if end_prev is None and self.rs.height > self.state.initial_height:
            return
        replaying = []
        if end_prev is not None:
            msgs = list(walmod.WAL.iter_messages(path))[end_prev:]
            replaying = msgs
        else:
            replaying = list(
                walmod.WAL.iter_messages(path)
            )
        if replaying:
            _log.info(
                "replaying WAL messages for current height",
                height=self.rs.height,
                count=len(replaying),
            )
        for m in replaying:
            try:
                self._replay_one(m)
            except Exception:
                traceback.print_exc()

    def _replay_one(self, m: walmod.WALMessage) -> None:
        from ..store.block_store import _decode_part

        if m.kind == walmod.MSG_PROPOSAL:
            self._set_proposal(codec.decode_proposal(m.data))
        elif m.kind == walmod.MSG_BLOCK_PART:
            self._add_proposal_block_part(
                m.height, m.round, _decode_part(m.data)
            )
        elif m.kind == walmod.MSG_VOTE:
            self._try_add_vote(codec.decode_vote(m.data), m.peer_id)

    def _reconcile_privval_state(self) -> None:
        """Group-commit recovery: a crash between an own-vote append
        and its group fsync loses the WAL record, but the privval
        state file — fsync-persisted BEFORE the signature is ever
        released (privval/file_pv.py) — still holds the signed vote.
        Rebuild it from that authoritative record and feed it back
        through the normal own-vote path; without this, replay asks
        the signer for an already-passed step and every retry dies on
        DoubleSignError while the height wedges. No-op whenever the
        WAL already carried the vote (the strict serial path)."""
        pv = self.privval
        last = getattr(pv, "last", None)  # remote signers: no state
        if (
            last is None
            or not last.sign_bytes
            or not last.signature
            or last.height != self.rs.height
        ):
            return
        try:
            vote = self._vote_from_privval_state(last)
        except Exception:
            traceback.print_exc()
            return
        if vote is None:
            return
        vs = (
            self.rs.votes.prevotes(vote.round)
            if vote.type_ == T.PREVOTE
            else self.rs.votes.precommits(vote.round)
        )
        if (
            vs is None
            or not 0 <= vote.validator_index < len(vs.votes)
            or vs.votes[vote.validator_index] is not None
        ):
            return  # replayed from the WAL — nothing was lost
        if vote.type_ == T.PRECOMMIT and not vote.block_id.is_nil():
            rs = self.rs
            have_block = (
                rs.proposal_block is not None
                and rs.proposal_block.hash() == vote.block_id.hash
            ) or (
                rs.proposal_block_parts is not None
                and rs.proposal_block_parts.header.hash
                == vote.block_id.part_set_header.hash
            )
            val = rs.validators.get_by_index(vote.validator_index)
            alone_quorum = (
                val is not None
                and val.voting_power * 3
                > rs.validators.total_voting_power() * 2
            )
            if not have_block and alone_quorum:
                # the WAL lost the block this precommit binds to
                # (crash inside the same group window) and our own
                # power forms a quorum: injecting the vote would
                # drive _enter_commit into waiting forever for parts
                # that exist nowhere. Roll the signer back instead —
                # see _rollback_privval_to_wal for why that is safe.
                self._rollback_privval_to_wal(vote)
                return
        _log.info(
            "reconciling own vote lost from WAL tail (privval state "
            "is authoritative)",
            height=vote.height,
            round=vote.round,
            type=vote.type_,
        )
        self._commit_own_vote(vote)

    def _rollback_privval_to_wal(self, vote: T.Vote) -> None:
        """Reset the signer's last-sign state to the newest own record
        the fsync'd WAL holds.

        Safe because externalization is gated on durability: a
        broadcast fires only after its record's covering fsync
        (_after_durable), on the strict path and the group path
        alike — so a vote present in the privval state but ABSENT
        from the WAL was provably never sent to anyone, and
        re-signing at that HRS cannot put conflicting signatures on
        the wire. (Prefix-ordered durability extends the proof
        backward: if this precommit never fsync'd, neither did
        anything we wrote after the last WAL-backed record.) The one
        unprovable case — an operator deleting the WAL while keeping
        the privval state — is exactly the setup the reference's
        double-sign protection cannot distinguish either."""
        from ..privval.file_pv import (
            _LastSign,
            STEP_PRECOMMIT,
            STEP_PREVOTE,
        )

        rs = self.rs
        idx = vote.validator_index
        newest = None  # (vote, privval step) from the replayed WAL
        for r in range(vote.round, -1, -1):
            for vset, step in (
                (rs.votes.precommits(r), STEP_PRECOMMIT),
                (rs.votes.prevotes(r), STEP_PREVOTE),
            ):
                v = (
                    vset.votes[idx]
                    if vset is not None and idx < len(vset.votes)
                    else None
                )
                if v is not None and v.signature:
                    newest = (v, step)
                    break
            if newest is not None:
                break
        if newest is None:
            new_last = _LastSign(height=vote.height, round=0, step=0)
        else:
            v, step = newest
            new_last = _LastSign(
                height=v.height,
                round=v.round,
                step=step,
                signature=v.signature.hex(),
                sign_bytes=v.sign_bytes(self.state.chain_id).hex(),
            )
        _log.info(
            "rolling back privval state to the newest WAL-proven "
            "record (lost vote was never externalized)",
            height=vote.height,
            round=vote.round,
            lost_type=vote.type_,
            restored_step=new_last.step,
        )
        self.privval.last = new_last
        try:
            self.privval.save_state()
        except Exception:
            traceback.print_exc()

    def _vote_from_privval_state(self, last) -> Optional[T.Vote]:
        """Decode FilePV's canonical sign bytes back into our Vote;
        None when it isn't a vote of ours for this height (proposals,
        other chains, valsets we left)."""
        from ..utils import proto

        sb = bytes.fromhex(last.sign_bytes)
        payload, _ = proto.read_delimited(sb)
        m = proto.parse(payload)
        type_c = proto.get1(m, 1, 0)
        if type_c not in (T.PREVOTE, T.PRECOMMIT):
            return None
        chain = proto.get1(m, 6, b"").decode()
        if chain != self.state.chain_id:
            return None
        bid_raw = proto.get1(m, 4, None)
        if bid_raw is None:
            bid = T.NIL_BLOCK_ID
        else:
            bm = proto.parse(bid_raw)
            pm = proto.parse(proto.get1(bm, 2, b""))
            bid = T.BlockID(
                proto.get1(bm, 1, b""),
                T.PartSetHeader(
                    proto.get1(pm, 1, 0), proto.get1(pm, 2, b"")
                ),
            )
        if (
            self.state.consensus_params.vote_extensions_enabled(
                last.height
            )
            and type_c == T.PRECOMMIT
            and not bid.is_nil()
        ):
            # the extension payload/signature are not in the privval
            # state; a rebuilt extensionless precommit would be
            # rejected by every peer's VerifyVoteExtension gate
            return None
        addr = self.privval.pub_key().address()
        idx, val = self.rs.validators.get_by_address(addr)
        if idx < 0 or val is None:
            return None
        vote = T.Vote(
            type_=type_c,
            height=last.height,
            round=last.round,
            block_id=bid,
            timestamp_ns=proto.parse_timestamp(
                proto.get1(m, 5, b"")
            ),
            validator_address=addr,
            validator_index=idx,
            signature=bytes.fromhex(last.signature),
        )
        # the rebuilt encoding must reproduce the signed bytes exactly
        # or the signature is for something else — refuse to inject
        if vote.sign_bytes(chain) != sb:
            return None
        return vote

    # --- timeout scheduling -------------------------------------------

    def _schedule_timeout(
        self, duration_s: float, height: int, round_: int, step: Step
    ) -> None:
        if self._timeout_task is not None:
            self._timeout_task.cancel()
        ti = TimeoutInfo(duration_s, height, round_, step)

        async def fire():
            try:
                if duration_s > 0:
                    await asyncio.sleep(duration_s)
                await self.queue.put(("timeout", ti, ""))
            except asyncio.CancelledError:
                pass

        self._timeout_task = asyncio.create_task(fire())

    # --- round entry functions ----------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != Step.NEW_HEIGHT
        ):
            return
        if round_ > rs.round:
            vals = rs.validators.copy()
            vals.increment_proposer_priority(round_ - rs.round)
            rs.validators = vals
        # close the previous round's open spans (step first — LIFO)
        # so the new round's spans nest cleanly under the height span
        self._close_trace_spans("_sp_step", "_sp_round")
        _log.debug("entering new round", height=height, round=round_)
        self._round_t0_ns = time.monotonic_ns()
        rs.round = round_
        rs.step = Step.NEW_ROUND
        if round_ > 0:
            # new round: reset proposal (keep locked/valid blocks)
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_)
        rs.triggered_timeout_precommit = False
        self.event_bus.publish_type(
            ev.EVENT_NEW_ROUND, {"height": height, "round": round_}
        )
        self._new_step()
        # wait for txs? (create_empty_blocks interval) — proceed directly
        self._enter_propose(height, round_)

    def _enter_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= Step.PROPOSE
        ):
            return
        _log.debug("entering propose step", height=height, round=round_)
        rs.step = Step.PROPOSE
        self._new_step()
        self._schedule_timeout(
            self.config.propose_timeout(round_), height, round_, Step.PROPOSE
        )
        if self.privval is None:
            self._maybe_finish_propose(height, round_)
            return
        try:
            our_addr = self.privval.pub_key().address()
        except Exception:
            # remote signer unavailable; propose timeout cycles round
            self._maybe_finish_propose(height, round_)
            return
        if not rs.validators.has_address(our_addr):
            self._maybe_finish_propose(height, round_)
            return
        proposer = rs.validators.get_proposer()
        if proposer.address == our_addr:
            self._decide_proposal(height, round_)
        self._maybe_finish_propose(height, round_)

    def _maybe_finish_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.proposal_block is not None and rs.proposal is not None:
            self._enter_prevote(height, round_)

    def _decide_proposal(self, height: int, round_: int) -> None:
        """We are the proposer (reference defaultDecideProposal :1246)."""
        rs = self.rs
        if rs.valid_block is not None:
            block, parts = rs.valid_block, rs.valid_block_parts
        else:
            last_commit = None
            if height > self.state.initial_height:
                seen = self.block_store.load_seen_commit(height - 1)
                last_commit = seen
                if last_commit is None and rs.last_commit is not None:
                    last_commit = rs.last_commit.make_commit()
                if last_commit is None:
                    return  # cannot propose without last commit
            extended_commit = None
            if (
                height > self.state.initial_height
                and self.state.consensus_params.vote_extensions_enabled(
                    height - 1
                )
            ):
                raw = self.block_store.load_extended_commit(height - 1)
                try:
                    extended_commit = (
                        codec.decode_extended_commit(raw) if raw else None
                    )
                except Exception:
                    traceback.print_exc()
                    extended_commit = None
                if extended_commit is None:
                    # extensions were promised to the app; proposing a
                    # plain CommitInfo instead would silently violate
                    # the ABCI contract (reference panics here). Skip
                    # this proposal — another proposer that holds the
                    # extended commit takes the next round.
                    _log.error(
                        "no extended commit for previous height; "
                        "refusing to propose",
                        height=height,
                    )
                    return
            try:
                block, parts = self.block_exec.create_proposal_block(
                    height,
                    self.state,
                    last_commit,
                    self.privval.pub_key().address(),
                    extended_commit=extended_commit,
                )
            except Exception:
                traceback.print_exc()
                return
        bid = T.BlockID(block.hash(), parts.header)
        _log.info(
            "proposing block",
            height=height,
            round=round_,
            hash=Lazy(lambda: block.hash()[:8].hex()),
            txs=len(block.data.txs),
        )
        prop = T.Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=bid,
            timestamp_ns=time.time_ns(),
        )
        if getattr(self.privval, "REMOTE_BLOCKING", False) and self.queue:
            chain_id = self.state.chain_id

            async def sign_off_loop():
                try:
                    await asyncio.to_thread(
                        self.privval.sign_proposal, chain_id, prop
                    )
                except asyncio.CancelledError:
                    raise  # consensus stop cancels in-flight signs
                except Exception:
                    traceback.print_exc()
                    return  # propose timeout moves the round along
                self.enqueue_nowait("signed_proposal", (prop, parts), "")

            spawn(sign_off_loop(), name="privval-sign-off")
            return
        try:
            self.privval.sign_proposal(self.state.chain_id, prop)
        except Exception:
            traceback.print_exc()
            return
        self._publish_own_proposal(prop, parts)

    def _publish_own_proposal(self, prop: T.Proposal, parts) -> None:
        """Feed our signed proposal + parts to ourselves and the
        gossip hooks (we ARE the single writer here)."""
        rs = self.rs
        if prop.height != rs.height or prop.round != rs.round:
            return  # round moved on while signing remotely
        tprop = self._wal_write_msg("proposal", ProposalMessage(prop), "")
        self._set_proposal(prop)
        self._after_durable(
            tprop,
            lambda: self._broadcast("proposal", ProposalMessage(prop)),
        )
        for i in range(parts.header.total):
            part = parts.get_part(i)
            msg = BlockPartMessage(prop.height, prop.round, part)
            # one fsync typically covers the proposal + every part
            # (the group window): the proposer's worst per-height
            # fsync storm collapses to one barrier
            tpart = self._wal_write_msg("block_part", msg, "")
            self._add_proposal_block_part(prop.height, prop.round, part)
            self._after_durable(
                tpart, lambda m=msg: self._broadcast("block_part", m)
            )

    def _set_proposal(self, proposal: T.Proposal) -> bool:
        rs = self.rs
        if rs.proposal is not None:
            return False
        if proposal.height != rs.height or proposal.round != rs.round:
            return False
        proposal.validate_basic()
        proposer = rs.validators.get_proposer()
        if not proposal.verify(self.state.chain_id, proposer.pub_key):
            raise ValueError("invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = T.PartSet(proposal.block_id.part_set_header)
        return True

    def _add_proposal_block_part(
        self, height: int, round_: int, part: T.Part
    ) -> bool:
        rs = self.rs
        if height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(part)
        if not added:
            return False
        if rs.proposal_block_parts.is_complete():
            data = rs.proposal_block_parts.assemble()
            block = codec.decode_block(data)
            rs.proposal_block = block
            # attribution mark: proposal fully propagated to this node
            self._proposal_complete_ns = time.monotonic_ns()
            self.tracer.instant(
                "consensus.proposal.complete", tid="consensus",
                height=height, round=rs.round,
            )
            self.event_bus.publish_type(
                ev.EVENT_COMPLETE_PROPOSAL,
                {"height": height, "block_id": rs.proposal.block_id if rs.proposal else None},
            )
            # prevotes may already have a polka for this block
            prevotes = rs.votes.prevotes(rs.round)
            bid = prevotes.two_thirds_majority()
            if bid is not None and not bid.is_nil() and rs.valid_round < rs.round:
                if block.hash() == bid.hash:
                    rs.valid_round = rs.round
                    rs.valid_block = block
                    rs.valid_block_parts = rs.proposal_block_parts
            if rs.step <= Step.PROPOSE and rs.proposal is not None:
                self._enter_prevote(height, rs.round)
            elif rs.step == Step.COMMIT:
                self._try_finalize_commit(height)
        return True

    # --- prevote ------------------------------------------------------

    def _enter_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= Step.PREVOTE
        ):
            return
        _log.debug("entering prevote step", height=height, round=round_)
        rs.step = Step.PREVOTE
        self._new_step()
        self._do_prevote(height, round_)

    def _do_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        # locked block? vote for it (reference defaultDoPrevote :1387)
        if rs.locked_block is not None:
            self._sign_add_vote(
                T.PREVOTE,
                rs.locked_block.hash(),
                rs.locked_block_parts.header,
            )
            return
        if rs.proposal_block is None:
            self._sign_add_vote(T.PREVOTE, None, None)
            return
        # validate (spanned: the "verify" leg of the per-height
        # commit-latency waterfall, docs/TRACE.md)
        t_verify = time.monotonic_ns()
        try:
            self.block_exec.validate_block(
                self.state, rs.proposal_block, priority=T.PRIORITY_LIVE
            )
            accepted = self.block_exec.process_proposal(
                rs.proposal_block, self.state
            )
        except Exception:
            accepted = False
        self._verify_ns = time.monotonic_ns() - t_verify
        self.tracer.complete(
            "consensus.verify", t_verify, self._verify_ns,
            tid="consensus", height=height, round=round_,
            accepted=accepted,
        )
        if accepted:
            self._sign_add_vote(
                T.PREVOTE,
                rs.proposal_block.hash(),
                rs.proposal_block_parts.header,
            )
        else:
            self._sign_add_vote(T.PREVOTE, None, None)

    # --- precommit ----------------------------------------------------

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= Step.PREVOTE_WAIT
        ):
            return
        rs.step = Step.PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(
            self.config.prevote_timeout(round_),
            height,
            round_,
            Step.PREVOTE_WAIT,
        )

    def _enter_precommit(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= Step.PRECOMMIT
        ):
            return
        _log.debug("entering precommit step", height=height, round=round_)
        rs.step = Step.PRECOMMIT
        self._new_step()
        prevotes = rs.votes.prevotes(round_)
        bid = prevotes.two_thirds_majority()
        if bid is None:
            # no polka: precommit nil
            self._sign_add_vote(T.PRECOMMIT, None, None)
            return
        self.event_bus.publish_type(
            ev.EVENT_POLKA, {"height": height, "round": round_}
        )
        if bid.is_nil():
            # polka for nil: unlock
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            self._sign_add_vote(T.PRECOMMIT, None, None)
            return
        # polka for a block
        if rs.locked_block is not None and rs.locked_block.hash() == bid.hash:
            rs.locked_round = round_
            self._sign_add_vote(T.PRECOMMIT, bid.hash, bid.part_set_header)
            return
        if rs.proposal_block is not None and rs.proposal_block.hash() == bid.hash:
            try:
                self.block_exec.validate_block(
                    self.state,
                    rs.proposal_block,
                    priority=T.PRIORITY_LIVE,
                )
                rs.locked_round = round_
                rs.locked_block = rs.proposal_block
                rs.locked_block_parts = rs.proposal_block_parts
                self.event_bus.publish_type(
                    ev.EVENT_LOCK, {"height": height, "round": round_}
                )
                self._sign_add_vote(
                    T.PRECOMMIT, bid.hash, bid.part_set_header
                )
                return
            except Exception:
                traceback.print_exc()
        # polka for a block we don't have: unlock, precommit nil
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        self._sign_add_vote(T.PRECOMMIT, None, None)

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(
            self.config.precommit_timeout(round_),
            height,
            round_,
            Step.PRECOMMIT_WAIT,
        )

    # --- commit -------------------------------------------------------

    def _enter_commit(self, height: int, commit_round: int) -> None:
        rs = self.rs
        if rs.height != height or rs.step >= Step.COMMIT:
            return
        _log.debug(
            "entering commit step", height=height, round=commit_round
        )
        rs.step = Step.COMMIT
        rs.commit_round = commit_round
        rs.commit_time_ns = time.time_ns()
        self._new_step()
        bid = rs.votes.precommits(commit_round).two_thirds_majority()
        assert bid is not None and not bid.is_nil()
        # if we have the block already as locked/proposal, stage it
        if rs.locked_block is not None and rs.locked_block.hash() == bid.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if (
            rs.proposal_block is None
            or rs.proposal_block.hash() != bid.hash
        ):
            # we're missing the block: reset parts to fetch it
            if (
                rs.proposal_block_parts is None
                or rs.proposal_block_parts.header.hash != bid.part_set_header.hash
            ):
                rs.proposal_block = None
                rs.proposal_block_parts = T.PartSet(bid.part_set_header)
            return
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height or rs.step != Step.COMMIT:
            return
        if self._finalize_inflight is not None:
            return  # single in-flight height: the pipeline's barrier
        bid = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        if bid is None or bid.is_nil():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != bid.hash:
            return
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        rs = self.rs
        block, parts = rs.proposal_block, rs.proposal_block_parts
        bid = T.BlockID(block.hash(), parts.header)
        seen_commit = rs.votes.precommits(rs.commit_round).make_commit()
        if self.state.consensus_params.vote_extensions_enabled(height):
            # persist the extension payloads alongside the block so
            # the proposer can feed them to the NEXT height's
            # PrepareProposal (reference SaveBlockWithExtendedCommit)
            try:
                ec = rs.votes.precommits(
                    rs.commit_round
                ).make_extended_commit()
                self.block_store.save_extended_commit(
                    height, codec.encode_extended_commit(ec)
                )
            except Exception:
                traceback.print_exc()
        # persist + WAL end-height barrier (reference :1775-1801) +
        # apply + advance (commit already verified by consensus itself)
        if self.config.finalize_pipeline and self.queue is not None:
            self._start_pipelined_finalize(block, parts, seen_commit, bid)
            return
        self._apply_committed_block(
            block, parts, seen_commit, bid, immediate=False
        )

    def _start_pipelined_finalize(self, block, parts, commit, bid) -> None:
        """Run the finalize tail off-loop so the receive routine keeps
        relaying gossip (votes/parts/catch-up) during persist + fsync +
        apply. Bounded to one in-flight height: _try_finalize_commit
        refuses to start another until _complete_finalize lands, and
        the next height only opens there — the barrier before the next
        commit is structural."""
        height = block.height
        self._finalize_inflight = height
        # NOTE: _parked is NOT cleared — messages for height+1 parked
        # before the commit quorum landed are exactly what the replay
        # at _complete_finalize exists to deliver

        async def run():
            try:
                # the disk legs go off-loop: the loop keeps relaying
                # votes/parts while sqlite + the end-height fsync
                # grind
                t_fin, t_persist, t_wal = await asyncio.to_thread(
                    self._finalize_persist, block, parts, commit
                )
                if self.config.finalize_offload_apply:
                    # native finalize lane (state/native_finalize.py):
                    # the ABCI dispatch stays on-loop (app-owned,
                    # GIL-ful), but the hash/encode/persist leg —
                    # which the native pass runs with the GIL
                    # RELEASED — rides a second thread hop, so the
                    # loop relays gossip through it too. Same phase
                    # order and fail points as the serial apply_block.
                    t0 = time.monotonic()
                    resp = self.block_exec.apply_finalize(
                        self.state, block, verified=True
                    )
                    def hash_persist():
                        # timed THREAD-SIDE: the span is the leg the
                        # native lane owns (tx hashes, result encodes,
                        # LastResultsHash, event encodes, the response
                        # write) without the loop-resume latency of
                        # the to_thread hop, which on a saturated box
                        # dwarfs the work itself
                        t_a = time.monotonic_ns()
                        out = self.block_exec.apply_hash_persist(
                            self.state, bid, block, resp
                        )
                        return out, t_a, time.monotonic_ns()

                    (new_state, artifacts), t_a, t_b = (
                        await asyncio.to_thread(hash_persist)
                    )
                    self.tracer.complete(
                        "consensus.finalize.hash_persist", t_a,
                        t_b - t_a,
                        tid="consensus", height=block.height,
                        native=artifacts.native,
                    )
                    new_state = self.block_exec.apply_complete(
                        new_state, bid, block, resp, artifacts, t0
                    )
                    t_apply = time.monotonic_ns()
                    fail_point("cs-after-apply")  # :1837
                    timings = (
                        new_state, t_fin, t_persist, t_wal, t_apply
                    )
                else:
                    # legacy shape: the whole (pure-Python, GIL-bound)
                    # ABCI apply runs back on the loop exactly like
                    # the serial path
                    timings = self._finalize_apply(
                        block, bid, t_fin, t_persist, t_wal
                    )
            except asyncio.CancelledError:
                raise
            except Exception:
                _log.error(
                    "pipelined finalize failed", height=height
                )
                traceback.print_exc()
                # release the barrier: a later precommit/part retries
                # through _try_finalize_commit (the tail is idempotent
                # — save_block is height-guarded, end-height re-marks;
                # parked next-height messages stay parked for the
                # retry's completion)
                self._finalize_inflight = None
                return
            self._complete_finalize(
                block, bid, timings, immediate=False, pipelined=True
            )

        self._finalize_task = spawn(run(), name="finalize-pipeline")

    # --- votes --------------------------------------------------------

    def _sign_add_vote(
        self,
        type_: int,
        block_hash: Optional[bytes],
        psh: Optional[T.PartSetHeader],
    ) -> None:
        rs = self.rs
        if self.privval is None:
            return
        try:
            addr = self.privval.pub_key().address()
        except Exception:
            traceback.print_exc()
            self._schedule_sign_retry(
                type_, block_hash, psh, rs.height, rs.round
            )
            return
        if not rs.validators.has_address(addr):
            return
        idx, _ = rs.validators.get_by_address(addr)
        bid = (
            T.BlockID(block_hash, psh)
            if block_hash is not None
            else T.NIL_BLOCK_ID
        )
        vote = T.Vote(
            type_=type_,
            height=rs.height,
            round=rs.round,
            block_id=bid,
            timestamp_ns=time.time_ns(),
            validator_address=addr,
            validator_index=idx,
        )
        want_ext = (
            type_ == T.PRECOMMIT
            and not bid.is_nil()
            and self.state.consensus_params.vote_extensions_enabled(rs.height)
        )
        if want_ext:
            # the APP authors the extension content (reference
            # consensus/state.go ExtendVote -> ABCI boundary). A
            # failure must NOT degrade to signing an empty extension:
            # peers' VerifyVoteExtension would reject the whole
            # precommit, silently equivalent to not voting — retry
            # instead (the app may be restarting).
            try:
                vote.extension = self.block_exec.extend_vote(
                    bid.hash, rs.height, rs.round, vote.timestamp_ns
                )
            except Exception:
                _log.error(
                    "ExtendVote failed; retrying vote",
                    height=rs.height,
                    round=rs.round,
                )
                traceback.print_exc()
                self._schedule_sign_retry(
                    type_, block_hash, psh, rs.height, rs.round
                )
                return
        if getattr(self.privval, "REMOTE_BLOCKING", False) and self.queue:
            # remote signer: a socket round trip must not block the
            # event loop — sign in a worker thread and feed the signed
            # vote back through the single-writer queue
            chain_id = self.state.chain_id

            def do_sign():
                self.privval.sign_vote(chain_id, vote)
                if want_ext:
                    self.privval.sign_vote_extension(chain_id, vote)

            async def sign_off_loop():
                try:
                    await asyncio.to_thread(do_sign)
                except asyncio.CancelledError:
                    raise  # consensus stop cancels in-flight signs
                except Exception as e:
                    from ..privval import DoubleSignError

                    traceback.print_exc()
                    if not isinstance(e, DoubleSignError):
                        self._schedule_sign_retry(
                            type_, block_hash, psh, vote.height, vote.round
                        )
                    return
                self.enqueue_nowait("signed_vote", VoteMessage(vote), "")

            spawn(sign_off_loop(), name="privval-sign-off")
            return
        try:
            self.privval.sign_vote(self.state.chain_id, vote)
            if want_ext:
                self.privval.sign_vote_extension(self.state.chain_id, vote)
        except Exception as e:
            from ..privval import DoubleSignError

            traceback.print_exc()
            if isinstance(e, DoubleSignError):
                # permanent: the signer's state is AHEAD of this ask
                # (e.g. group-commit recovery rebuilt an earlier step)
                # — retrying the same HRS can never succeed, and the
                # privval-state reconciliation / round progression is
                # what recovers liveness
                return
            # signing can fail transiently (remote signer down):
            # retry while the round is still current, else a lone or
            # pivotal validator stalls forever even after the signer
            # returns. Safe: FilePV re-serves the signature for votes
            # differing only by timestamp, so no double-sign risk.
            self._schedule_sign_retry(
                type_, block_hash, psh, rs.height, rs.round
            )
            return
        self._commit_own_vote(vote)

    def _check_vote_extension(self, vote: T.Vote) -> None:
        """Peer-vote extension rules (reference consensus/state.go
        addVote -> VerifyVoteExtension boundary):

        - extensions disabled, or a prevote, or a nil precommit: any
          extension data is rejected (byzantine padding would otherwise
          be stored, gossiped, and fed to the app against the ABCI
          contract);
        - extensions enabled + non-nil precommit: the extension
          signature must verify and the app must accept — checked only
          for NEW votes (duplicates short-circuit before the ed25519 +
          ABCI round trip).
        """
        rs = self.rs
        enabled = self.state.consensus_params.vote_extensions_enabled(
            vote.height
        )
        is_ext_precommit = (
            enabled
            and vote.type_ == T.PRECOMMIT
            and not vote.block_id.is_nil()
        )
        if not is_ext_precommit:
            if vote.extension or vote.extension_signature:
                raise ValueError(
                    "unexpected vote extension data (disabled height, "
                    "prevote, or nil precommit)"
                )
            return
        # duplicate? the vote set dedups cheaply; don't pay the
        # signature + app round trip again for re-gossiped votes
        existing = rs.votes.precommits(vote.round).get_vote(
            vote.validator_index
        ) if 0 <= vote.validator_index < rs.validators.size() else None
        if (
            existing is not None
            and existing.block_id.key() == vote.block_id.key()
        ):
            return
        val = rs.validators.get_by_index(vote.validator_index)
        if val is None or not vote.extension_signature:
            raise ValueError("missing vote extension signature")
        if not val.pub_key.verify(
            vote.extension_sign_bytes(self.state.chain_id),
            vote.extension_signature,
        ):
            raise ValueError("invalid vote extension signature")
        if not self.block_exec.verify_vote_extension(vote):
            raise ValueError("app rejected vote extension")

    def _commit_own_vote(self, vote: T.Vote) -> None:
        ticket = self._wal_write_msg("vote", VoteMessage(vote), "")
        self._try_add_vote(vote, "")
        # WAL-before-act: the group-commit seam defers the BROADCAST
        # (the externalization that must never precede durability)
        # until the vote's barrier fsync lands; adding to our own
        # vote set above is in-memory only and crash-consistent
        self._after_durable(
            ticket, lambda: self._broadcast("vote", VoteMessage(vote))
        )

    def _schedule_sign_retry(
        self, type_, block_hash, psh, height: int, round_: int
    ) -> None:
        if self.queue is None:
            return

        async def retry():
            await asyncio.sleep(1.0)
            try:
                self.queue.put_nowait(
                    ("retry_sign", (type_, block_hash, psh, height, round_), "")
                )
            except asyncio.QueueFull:
                pass

        spawn(retry(), name="sign-retry")

    def _handle_sign_retry(self, payload) -> None:
        type_, block_hash, psh, height, round_ = payload
        rs = self.rs
        if rs.height != height or rs.round != round_:
            return  # round moved on; normal flow takes over
        if rs.votes is not None:
            vs = (
                rs.votes.prevotes(round_)
                if type_ == T.PREVOTE
                else rs.votes.precommits(round_)
            )
            if vs is not None and self.privval is not None:
                try:
                    addr = self.privval.pub_key().address()
                    idx, _ = rs.validators.get_by_address(addr)
                    if idx >= 0 and vs.votes[idx] is not None:
                        return  # already signed + added
                except Exception:
                    # signer STILL down (the very case retries exist
                    # for): keep the chain of retries alive
                    self._schedule_sign_retry(
                        type_, block_hash, psh, height, round_
                    )
                    return
        self._sign_add_vote(type_, block_hash, psh)

    def _try_add_vote(self, vote: T.Vote, peer_id: str) -> None:
        rs = self.rs
        try:
            if vote.height + 1 == rs.height and vote.type_ == T.PRECOMMIT:
                # late precommit for the previous height
                if rs.last_commit is not None:
                    try:
                        rs.last_commit.add_vote(vote)
                    except Exception:
                        pass
                return
            if vote.height != rs.height:
                return
            if peer_id != "":
                self._check_vote_extension(vote)
            added = rs.votes.add_vote(vote)
            if not added:
                return
        except T.ErrVoteConflictingVotes as e:
            if self.evpool is not None and peer_id != "":
                _, val = rs.validators.get_by_address(vote.validator_address)
                if val is not None:
                    from ..evidence.types import DuplicateVoteEvidence

                    evd = DuplicateVoteEvidence.from_votes(
                        e.existing,
                        e.new,
                        val.voting_power,
                        rs.validators.total_voting_power(),
                        time.time_ns(),
                    )
                    _log.info(
                        "found conflicting vote, adding evidence",
                        height=vote.height,
                        round=vote.round,
                        validator=vote.validator_address.hex()[:12],
                    )
                    try:
                        self.evpool.add_evidence(evd)
                    except Exception:
                        pass
            return
        except Exception as e:
            _log.error(
                "failed to add vote",
                height=vote.height,
                round=vote.round,
                type=vote.type_,
                peer=peer_id,
                err=repr(e),
            )
            return
        self.event_bus.publish_type(ev.EVENT_VOTE, vote)
        if self.tracer.enabled and vote.height == rs.height:
            self._record_vote_arrival(vote, peer_id)
        if peer_id != "":
            self._broadcast("vote", VoteMessage(vote))
        height, round_ = rs.height, rs.round
        if vote.type_ == T.PREVOTE:
            prevotes = rs.votes.prevotes(vote.round)
            bid = prevotes.two_thirds_majority()
            if bid is not None and not bid.is_nil():
                self._record_quorum(vote.round, "prevote")
                # unlock if POL for something else (reference :2274)
                if (
                    rs.locked_block is not None
                    and rs.locked_round < vote.round <= rs.round
                    and rs.locked_block.hash() != bid.hash
                ):
                    rs.locked_round = -1
                    rs.locked_block = None
                    rs.locked_block_parts = None
                # update valid block
                if (
                    rs.valid_round < vote.round <= rs.round
                    and rs.proposal_block is not None
                    and rs.proposal_block.hash() == bid.hash
                ):
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
            if vote.round == round_:
                if prevotes.has_two_thirds_majority():
                    self._enter_precommit(height, vote.round)
                elif (
                    rs.step == Step.PREVOTE and prevotes.has_two_thirds_any()
                ):
                    self._enter_prevote_wait(height, vote.round)
            elif vote.round > round_ and rs.votes.prevotes(
                vote.round
            ).has_two_thirds_any():
                self._enter_new_round(height, vote.round)
        else:  # PRECOMMIT
            precommits = rs.votes.precommits(vote.round)
            bid = precommits.two_thirds_majority()
            if bid is not None:
                if not bid.is_nil():
                    self._record_quorum(vote.round, "precommit")
                self._enter_new_round(height, vote.round)
                self._enter_precommit(height, vote.round)
                if not bid.is_nil():
                    self._enter_commit(height, vote.round)
                    self._try_finalize_commit(height)
                    if self.config.skip_timeout_commit and precommits.has_all():
                        pass
                else:
                    self._enter_precommit_wait(height, vote.round)
            elif vote.round >= round_ and precommits.has_two_thirds_any():
                self._enter_new_round(height, vote.round)
                self._enter_precommit_wait(height, vote.round)

    # --- commit-latency attribution (ISSUE 7) -------------------------

    def _record_quorum(self, round_: int, step: str) -> None:
        """First time ⅔ of voting power lands on a non-nil block for
        (height, round, step): record a pre-measured span from round
        entry to now — the time-to-quorum leg of the commit waterfall
        (rides the span→metrics bridge into
        consensus_quorum_latency_seconds{step})."""
        key = (round_, step)
        if key in self._quorum_at:
            return
        now = time.monotonic_ns()
        self._quorum_at[key] = now
        t0 = self._round_t0_ns or now
        self.tracer.complete(
            f"consensus.quorum.{step}", t0, max(0, now - t0),
            tid="consensus", height=self.rs.height, round=round_,
            step=step,
        )

    def _note_commit_breakdown(
        self, height: int, t_fin: int, t_persist: int, t_wal: int,
        t_apply: int,
    ) -> None:
        """Phase attribution for the height just committed, measured
        from this round's entry (monotonic, this node's clock). Phases
        that never happened on this node (ingest path, nil rounds) are
        simply absent. ``dominant`` names the largest DISJOINT segment
        of the commit timeline — what RPC health cites when latency
        degrades."""
        t0 = self._round_t0_ns or t_fin
        ms = 1e6
        rs = self.rs
        segments: Dict[str, float] = {}
        prop = self._proposal_complete_ns
        if prop >= t0:
            segments["proposal_ms"] = (prop - t0) / ms
        pv = self._quorum_at.get((rs.commit_round, "prevote"))
        pc = self._quorum_at.get((rs.commit_round, "precommit"))
        if pv is not None:
            segments["prevote_wait_ms"] = (
                pv - (prop if prop >= t0 else t0)
            ) / ms
        if pc is not None:
            segments["precommit_wait_ms"] = (pc - (pv or t0)) / ms
        segments["persist_ms"] = (t_persist - t_fin) / ms
        segments["wal_ms"] = (t_wal - t_persist) / ms
        segments["apply_ms"] = (t_apply - t_wal) / ms
        phases = {k: round(max(0.0, v), 3) for k, v in segments.items()}
        if pv is not None:
            phases["prevote_quorum_ms"] = round(max(0.0, (pv - t0) / ms), 3)
        if pc is not None:
            phases["precommit_quorum_ms"] = round(
                max(0.0, (pc - t0) / ms), 3
            )
        if self._verify_ns:
            # overlaps the prevote segment (it IS part of forming our
            # prevote); reported but excluded from `dominant`
            phases["verify_ms"] = round(self._verify_ns / ms, 3)
        phases["total_ms"] = round(max(0.0, (t_apply - t0) / ms), 3)
        dominant = max(segments, key=lambda k: segments[k])
        self.last_commit_breakdown = {
            "height": height,
            "round": rs.commit_round,
            "phases": phases,
            "dominant": dominant,
        }

    def _record_vote_arrival(self, vote, peer_id: str) -> None:
        """Per-peer vote-arrival skew: a span from the FIRST vote of
        this (round, type) wave to this vote's arrival, labeled by the
        delivering peer (self for our own votes). The bridge surfaces
        the latest value as a per-peer gauge."""
        now = time.monotonic_ns()
        fkey = (vote.round, vote.type_)
        first = self._vote_first.setdefault(fkey, now)
        self.tracer.complete(
            "consensus.vote.skew", first, max(0, now - first),
            tid="consensus",
            peer=peer_id[:12] if peer_id else "self",
            step="prevote" if vote.type_ == T.PREVOTE else "precommit",
            height=vote.height, round=vote.round,
        )

    # --- misc ---------------------------------------------------------

    def _close_trace_spans(
        self, *attrs: str
    ) -> None:
        """End the named open trace spans (default: the whole stack),
        always innermost-first — step ⊂ round ⊂ height must close
        LIFO or Perfetto's time-range nesting breaks. Every handle is
        None-guarded (replay/ingest paths open lazily)."""
        for attr in attrs or ("_sp_step", "_sp_round", "_sp_height"):
            sp = getattr(self, attr)
            if sp is not None:
                sp.end()
                setattr(self, attr, None)

    def _new_step(self) -> None:
        # step-span lifecycle: each step's span runs until the NEXT
        # step begins (the machine is event-driven, not call-scoped);
        # height/round spans open lazily so replay/ingest paths that
        # skip _enter_new_round still nest correctly
        sp = self._sp_step
        if sp is not None:
            sp.end()
            self._sp_step = None
        if self.tracer.enabled:
            rs = self.rs
            if self._sp_height is None:
                self._sp_height = self.tracer.span(
                    "consensus.height", tid="consensus",
                    height=rs.height,
                )
            if self._sp_round is None:
                self._sp_round = self.tracer.span(
                    "consensus.round", tid="consensus",
                    height=rs.height, round=rs.round,
                )
            self._sp_step = self.tracer.span(
                "consensus.step", tid="consensus",
                height=rs.height, round=rs.round, step=rs.step.name,
            )
        self.event_bus.publish_type(
            ev.EVENT_NEW_ROUND_STEP,
            {
                "height": self.rs.height,
                "round": self.rs.round,
                "step": int(self.rs.step),
            },
        )

    # external API for reactors
    async def enqueue(self, kind: str, payload, peer_id: str) -> None:
        await self.queue.put((kind, payload, peer_id))

    def enqueue_nowait(self, kind: str, payload, peer_id: str) -> None:
        if self.queue is None:
            return  # not started yet (sync phase); drop
        try:
            self.queue.put_nowait((kind, payload, peer_id))
        except asyncio.QueueFull:
            # overload shed: count it (obs telemetry), callers keep
            # their existing QueueFull handling
            self.queue.count_drop()
            raise
