"""Handshaker: sync the ABCI app with the block store on boot.

Parity with reference consensus/replay.go: Info handshake (:241),
ReplayBlocks (:288) — InitChain at genesis, then replay stored blocks
[appHeight+1 .. storeHeight] through FinalizeBlock/Commit. This is the
crash-recovery path: the store may be ahead of the app by any number of
blocks (the WAL covers the in-flight height separately).
"""

from __future__ import annotations

from typing import List, Optional

from .. import types as T
from ..abci import types as abci
from ..state.state_types import State
from ..state.execution import (
    BlockExecutor,
    encode_finalize_response,
    results_hash,
)


class Handshaker:
    def __init__(self, state_store, state: State, block_store, genesis_doc):
        self.state_store = state_store
        self.state = state
        self.block_store = block_store
        self.genesis = genesis_doc
        self.n_blocks_replayed = 0

    def handshake(self, proxy_app) -> State:
        info = proxy_app.query.info(abci.RequestInfo())
        app_height = info.last_block_height
        app_hash = info.last_block_app_hash
        state = self.replay_blocks(proxy_app, self.state, app_height, app_hash)
        return state

    def replay_blocks(
        self, proxy_app, state: State, app_height: int, app_hash: bytes
    ) -> State:
        store_height = self.block_store.height()
        if app_height == 0:
            # genesis: InitChain
            vals = [
                abci.ValidatorUpdate(
                    pub_key_type=v.pub_key.type_,
                    pub_key_bytes=v.pub_key.key_bytes,
                    power=v.voting_power,
                )
                for v in self.genesis.validators
            ]
            resp = proxy_app.consensus.init_chain(
                abci.RequestInitChain(
                    time_ns=self.genesis.genesis_time_ns,
                    chain_id=self.genesis.chain_id,
                    validators=vals,
                    app_state_bytes=self.genesis.app_state_bytes,
                    initial_height=self.genesis.initial_height,
                )
            )
            if state.last_block_height == 0:
                if resp.validators:
                    from ..crypto.keys import pubkey_from_type_bytes

                    nv = [
                        T.Validator(
                            pubkey_from_type_bytes(
                                u.pub_key_type, u.pub_key_bytes
                            ),
                            u.power,
                        )
                        for u in resp.validators
                    ]
                    vs = T.ValidatorSet(nv)
                    state.validators = vs
                    state.next_validators = vs.copy()
                if resp.app_hash:
                    state.app_hash = resp.app_hash
                self.state_store.save(state)
            app_hash = resp.app_hash or state.app_hash
            app_height = self.genesis.initial_height - 1

        if store_height == 0:
            return state

        # replay store blocks the app has not seen
        for h in range(app_height + 1, store_height + 1):
            block = self.block_store.load_block(h)
            if block is None:
                raise RuntimeError(f"missing block {h} during replay")
            req = abci.RequestFinalizeBlock(
                txs=block.data.txs,
                hash=block.hash(),
                height=h,
                time_ns=block.header.time_ns,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=block.header.proposer_address,
            )
            resp = proxy_app.consensus.finalize_block(req)
            proxy_app.consensus.commit()
            # persist the response: if the crash predated the original
            # apply, state re-derivation below needs exactly this
            # (reference ExecCommitBlock feeding replay recovery)
            self.state_store.save_finalize_block_response(
                h, encode_finalize_response(resp)
            )
            self.n_blocks_replayed += 1
            app_hash = resp.app_hash

        # state may lag the store by one block (crash between save_block
        # and state save): re-derive it
        if state.last_block_height < store_height:
            meta = self.block_store.load_block_meta(store_height)
            block = self.block_store.load_block(store_height)
            raw = self.state_store.load_finalize_block_response(store_height)
            from .execution_compat import rederive_state

            state = rederive_state(
                self.state_store, state, block, meta, raw
            )
        if state.app_hash != app_hash and app_hash:
            state.app_hash = app_hash
        return state
