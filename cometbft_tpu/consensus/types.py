"""Round state + height vote set (reference consensus/types/)."""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..analysis.runtime import sanitized_lock
from .. import types as T


class Step(enum.IntEnum):
    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


class HeightVoteSet:
    """Prevotes + precommits for every round of one height
    (reference consensus/types/height_vote_set.go)."""

    def __init__(
        self,
        chain_id: str,
        height: int,
        val_set: T.ValidatorSet,
        sig_cache=None,
    ):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.sig_cache = sig_cache
        self.round = 0
        self._prevotes: Dict[int, T.VoteSet] = {}  # bftlint: disable=ASY119 — keyed by round within ONE height; the whole HeightVoteSet is replaced on height advance (update_to_state)
        self._precommits: Dict[int, T.VoteSet] = {}  # bftlint: disable=ASY119 — keyed by round within ONE height; replaced on height advance together with _prevotes
        self._lock = sanitized_lock(
            threading.RLock(), "consensus.votes"
        )
        self.set_round(0)

    def _ensure(self, round_: int) -> None:
        if round_ not in self._prevotes:
            self._prevotes[round_] = T.VoteSet(
                self.chain_id, self.height, round_, T.PREVOTE, self.val_set,
                sig_cache=self.sig_cache,
            )
            self._precommits[round_] = T.VoteSet(
                self.chain_id, self.height, round_, T.PRECOMMIT, self.val_set,
                sig_cache=self.sig_cache,
            )

    def set_round(self, round_: int) -> None:
        with self._lock:
            self._ensure(round_)
            self._ensure(round_ + 1)
            self.round = round_

    def add_vote(self, vote: T.Vote) -> bool:
        with self._lock:
            self._ensure(vote.round)
            vs = (
                self._prevotes if vote.type_ == T.PREVOTE else self._precommits
            )[vote.round]
            return vs.add_vote(vote)

    def prevotes(self, round_: int) -> Optional[T.VoteSet]:
        with self._lock:
            self._ensure(round_)
            return self._prevotes[round_]

    def precommits(self, round_: int) -> Optional[T.VoteSet]:
        with self._lock:
            self._ensure(round_)
            return self._precommits[round_]

    def pol_info(self):
        """(round, blockID) of the most recent prevote polka, or (-1, None)."""
        with self._lock:
            for r in sorted(self._prevotes, reverse=True):
                bid = self._prevotes[r].two_thirds_majority()
                if bid is not None:
                    return r, bid
        return -1, None


@dataclass
class RoundState:
    height: int = 0
    round: int = 0
    step: Step = Step.NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0
    validators: Optional[T.ValidatorSet] = None
    proposal: Optional[T.Proposal] = None
    proposal_block: Optional[T.Block] = None
    proposal_block_parts: Optional[T.PartSet] = None
    locked_round: int = -1
    locked_block: Optional[T.Block] = None
    locked_block_parts: Optional[T.PartSet] = None
    valid_round: int = -1
    valid_block: Optional[T.Block] = None
    valid_block_parts: Optional[T.PartSet] = None
    votes: Optional[HeightVoteSet] = None
    commit_round: int = -1
    last_commit: Optional[T.VoteSet] = None
    last_validators: Optional[T.ValidatorSet] = None
    triggered_timeout_precommit: bool = False
