"""State re-derivation after a crash between block save and state save."""

from __future__ import annotations

from .. import types as T
from ..state.execution import decode_finalize_response, results_hash
from ..state.state_types import State


def rederive_state(state_store, state: State, block, meta, finalize_raw):
    """Rebuild the post-block state when the block store is one ahead of
    state.db (reference handshake replay edge case)."""
    if finalize_raw is None:
        raise RuntimeError(
            "cannot re-derive state: missing finalize response"
        )
    resp = decode_finalize_response(finalize_raw)
    nvals = state.next_validators.copy()
    if resp.validator_updates:
        from ..crypto.keys import pubkey_from_type_bytes

        nvals.update_with_change_set(
            [
                T.Validator(
                    pubkey_from_type_bytes(u.pub_key_type, u.pub_key_bytes),
                    u.power,
                )
                for u in resp.validator_updates
            ]
        )
    nvals.increment_proposer_priority(1)
    new_state = State(
        chain_id=state.chain_id,
        initial_height=state.initial_height,
        last_block_height=block.height,
        last_block_id=meta.block_id,
        last_block_time_ns=block.header.time_ns,
        validators=state.next_validators.copy(),
        next_validators=nvals,
        last_validators=state.validators.copy(),
        last_height_validators_changed=state.last_height_validators_changed,
        consensus_params=state.consensus_params,
        last_height_consensus_params_changed=(
            state.last_height_consensus_params_changed
        ),
        last_results_hash=results_hash(resp.tx_results),
        app_hash=resp.app_hash,
    )
    state_store.save(new_state)
    return new_state
