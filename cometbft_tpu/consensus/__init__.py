from .state import (  # noqa: F401
    BlockPartMessage,
    ConsensusState,
    ProposalMessage,
    TimeoutInfo,
    VoteMessage,
)
from .types import HeightVoteSet, RoundState, Step  # noqa: F401
from .wal import WAL, WALMessage  # noqa: F401
from .replay import Handshaker  # noqa: F401
