"""Consensus reactor: gossips proposals, block parts and votes over
p2p channels (reference consensus/reactor.go).

Channel layout mirrors the reference (consensus/reactor.go:27-30):
  0x20 state  — NewRoundStep, HasVote, HasPart announcements
  0x21 data   — Proposal, BlockPart, CommitBlock (catch-up)
  0x22 vote   — Vote

Delivery model: fast path is flood-with-dedup (the state machine
re-broadcasts every NEWLY-added artifact via its broadcast hooks;
duplicates die at VoteSet/PartSet level). Reliability comes from the
per-peer GOSSIP routine (reference gossipDataRoutine :611 /
gossipVotesRoutine :657): using each peer's announced round state
(NewRoundStep) and acknowledgements (HasVote/HasPart — sent for every
vote/part received, duplicate or not), the routine retransmits
whatever the peer still lacks until it advances. This heals both
startup races (votes flooded before the peer connected) and any
mid-round message loss. Lagging peers get whole committed blocks +
commits instead (CommitBlock — the reactor-level analog of the
reference's gossipDataForCatchup)."""

from __future__ import annotations

import asyncio
import struct
import time
import traceback
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple

from .. import types as T
from ..p2p.node_info import ChannelDescriptor
from ..p2p.reactor import Reactor
from ..store.block_store import _decode_part, _encode_part
from ..types import events as ev
from ..utils import codec, proto
from ..utils.log import get_logger
from .state import BlockPartMessage, ProposalMessage, VoteMessage
from .types import Step

_log = get_logger("consensus.reactor")

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22

MSG_NEW_ROUND_STEP = 0x01
MSG_PROPOSAL = 0x02
MSG_BLOCK_PART = 0x03
MSG_VOTE = 0x04
MSG_COMMIT_BLOCK = 0x05
MSG_HAS_VOTE = 0x06
MSG_HAS_PART = 0x07

RETRANSMIT_AFTER_S = 0.25
CATCHUP_RETRANSMIT_S = 1.0
# periodic NewRoundStep re-announce per peer: step announcements are
# otherwise only broadcast ON step transitions, so a node whose
# announcement was lost (partition blackhole, conn churn) leaves every
# peer's PeerRoundState stale FOREVER if it then wedges in one step —
# peers keep aiming catch-up at the wrong height and the node can
# never advance (the healed-minority consensus wedge the chaos
# compound partition x statesync_join surfaced: peers retransmitted
# height-2 commits at a node parked in height 3 for 150s+)
STEP_REANNOUNCE_S = 1.0
MAX_GOSSIP_VOTES_PER_TICK = 16
MAX_GOSSIP_PARTS_PER_TICK = 8


@dataclass
class CommitBlockMessage:
    block: T.Block
    commit: T.Commit
    # raw extended commit when the sender holds one for this height —
    # catch-up must propagate ECs like every other commit path
    # (reference SaveBlockWithExtendedCommit), or nodes that caught up
    # through consensus can never serve the EC to blocksync joiners
    ec_bytes: Optional[bytes] = None


@dataclass
class PeerRoundState:
    height: int = 0
    round: int = -1
    step: int = 0
    # (height, round, type, index) votes the peer is known to have
    has_votes: Set[Tuple[int, int, int, int]] = field(default_factory=set)
    # (height, round, part_index) parts the peer is known to have
    has_parts: Set[Tuple[int, int, int]] = field(default_factory=set)
    proposal_seen: bool = False


class PeerVoteCursor:
    """Incremental per-peer vote picker over VoteSet.vote_log.

    The old shape rescanned every vote set the peer could need on
    EVERY gossip tick — O(validators) per peer per tick, O(V^2)
    across the committee even at steady state (flagged by ASY117,
    slope measured by bench.py's scaling leg). The cursor reads each
    source log once (``vote_log[read:]``), stages what the peer has
    not acked into ``pending``, and retransmits only from there:
    a tick costs O(new votes + unacked), which is O(0) at steady
    state.

    Sources are the same sets the reference PickSendVote consults:
    prevotes/precommits for {peer round, our round, our round - 1}
    plus last-height precommits. ``pending`` is bounded by the
    per-height vote count and the whole cursor resets on height
    advance (mirroring the peer's own ``has_votes.clear()``).
    """

    __slots__ = ("height", "_read", "pending")

    def __init__(self):
        self.height = 0
        self._read: Dict[tuple, int] = {}
        # vote key -> [vote, last_sent_monotonic]
        self.pending: Dict[tuple, list] = {}

    def reset(self, height: int) -> None:
        self.height = height
        self._read.clear()
        self.pending.clear()

    def _ingest_log(self, skey: tuple, log, has) -> None:
        start = self._read.get(skey, 0)
        if start >= len(log):
            return
        for v in log[start:]:
            k = _vote_key(v)
            if k not in has and k not in self.pending:
                self.pending[k] = [v, 0.0]
        self._read[skey] = len(log)

    def ingest(self, rs, prs: "PeerRoundState") -> None:
        """Advance every source cursor; stage new unacked votes."""
        has = prs.has_votes
        if rs.votes is not None:
            rounds = {prs.round, rs.round, rs.round - 1}
            for r in sorted(x for x in rounds if x >= 0):
                pv = rs.votes.prevotes(r)
                if pv is not None:
                    self._ingest_log(("pv", r), pv.vote_log, has)
                pc = rs.votes.precommits(r)
                if pc is not None:
                    self._ingest_log(("pc", r), pc.vote_log, has)
        if rs.last_commit is not None:
            self._ingest_log(("lc",), rs.last_commit.vote_log, has)

    def due_votes(
        self,
        prs: "PeerRoundState",
        now: float,
        budget: int,
        after: float = RETRANSMIT_AFTER_S,
    ):
        """Drop acked entries, return up to ``budget`` votes due for
        (re)transmission, stamping their send time."""
        out = []
        has = prs.has_votes
        drop = []
        for k, entry in self.pending.items():
            if k in has:
                drop.append(k)
                continue
            if now - entry[1] > after:
                entry[1] = now
                out.append(entry[0])
                if len(out) >= budget:
                    break
        for k in drop:
            del self.pending[k]
        return out


# --- wire codecs --------------------------------------------------------


def encode_new_round_step(height: int, round_: int, step: int) -> bytes:
    return bytes([MSG_NEW_ROUND_STEP]) + struct.pack(
        ">qiB", height, round_, step
    )


def encode_proposal_msg(p: T.Proposal) -> bytes:
    return bytes([MSG_PROPOSAL]) + codec.encode_proposal(p)


def encode_block_part_msg(height: int, round_: int, part: T.Part) -> bytes:
    return (
        bytes([MSG_BLOCK_PART])
        + proto.field_varint(1, height)
        + proto.field_varint(2, round_ + 1)  # +1: round 0 must be present
        + proto.field_bytes(3, _encode_part(part))
    )


def encode_vote_msg(v: T.Vote) -> bytes:
    return bytes([MSG_VOTE]) + codec.encode_vote(v)


def encode_commit_block(
    block: T.Block, commit: T.Commit, ec_bytes: Optional[bytes] = None
) -> bytes:
    out = (
        bytes([MSG_COMMIT_BLOCK])
        + proto.field_bytes(1, codec.encode_block(block))
        + proto.field_bytes(2, codec.encode_commit(commit))
    )
    if ec_bytes:
        out += proto.field_bytes(3, ec_bytes)
    return out


def encode_has_vote(height: int, round_: int, type_: int, index: int) -> bytes:
    return bytes([MSG_HAS_VOTE]) + struct.pack(">qiBi", height, round_, type_, index)


def encode_has_part(height: int, round_: int, index: int) -> bytes:
    return bytes([MSG_HAS_PART]) + struct.pack(">qii", height, round_, index)


def _vote_key(v: T.Vote) -> Tuple[int, int, int, int]:
    return (v.height, v.round, v.type_, v.validator_index)


class ConsensusReactor(Reactor):
    name = "consensus"

    def __init__(self, cs, block_store, wait_sync: bool = False):
        super().__init__()
        self.cs = cs
        self.block_store = block_store
        # wait_sync: created during blocksync/statesync; gossip starts
        # after switch_to_consensus (reference conR.WaitSync)
        self.wait_sync = wait_sync
        self._gossip_tasks: Dict[str, asyncio.Task] = {}
        # async coalescing queue: a round's vote wave is verified in
        # one batch dispatch; results land in cs.sig_cache so the
        # state machine's inline verify is a cache hit
        # (crypto/coalesce.py; BASELINE.json north-star queue)
        from ..crypto.coalesce import CoalescingVerifier

        self.vote_verifier = CoalescingVerifier(cache=cs.sig_cache)

    def get_channels(self):
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=6, max_msg_size=1 << 20),
            ChannelDescriptor(DATA_CHANNEL, priority=10),
            ChannelDescriptor(VOTE_CHANNEL, priority=7, max_msg_size=1 << 20),
        ]

    # --- lifecycle ----------------------------------------------------

    async def start(self) -> None:
        self.cs.add_broadcast_hook(self._on_cs_broadcast)
        self.cs.event_bus.add_sync_listener(self._on_event)

    async def stop(self) -> None:
        for t in self._gossip_tasks.values():
            t.cancel()
        self._gossip_tasks.clear()

    def switch_to_consensus(self) -> None:
        """Called when blocksync finishes (reference
        consensus/reactor.go:121 SwitchToConsensus)."""
        self.wait_sync = False
        self._announce_step()

    # --- outbound (flood fast path) -----------------------------------

    def _on_cs_broadcast(self, kind: str, payload) -> None:
        if self.switch is None or self.wait_sync:
            return
        # proposal/part/vote broadcasts carry a trace-context stamp
        # (cross-node causal tracing, p2p/tracewire.py); has_vote/
        # has_part acks and round-step announcements stay raw — they
        # are not part of the commit-latency attribution chain
        if kind == "proposal":
            p = payload.proposal
            self.switch.broadcast(
                DATA_CHANNEL, encode_proposal_msg(p),
                tkind="proposal", height=p.height, round_=p.round,
            )
        elif kind == "block_part":
            self.switch.broadcast(
                DATA_CHANNEL,
                encode_block_part_msg(
                    payload.height, payload.round, payload.part
                ),
                tkind="block_part",
                height=payload.height, round_=payload.round,
            )
            # tell peers we have it so they stop retransmitting to us
            self.switch.broadcast(
                STATE_CHANNEL,
                encode_has_part(
                    payload.height, payload.round, payload.part.index
                ),
            )
        elif kind == "vote":
            v = payload.vote
            self.switch.broadcast(
                VOTE_CHANNEL, encode_vote_msg(v),
                tkind="vote", height=v.height, round_=v.round,
            )
            self.switch.broadcast(
                STATE_CHANNEL, encode_has_vote(*_vote_key(payload.vote))
            )

    def _submit_vote(self, vote: T.Vote, peer_id: str) -> None:
        """Route an inbound vote through the coalescing verifier when
        it belongs to the current height's validator set; anything else
        (catch-up votes, unknown indexes) goes straight to the state
        machine, whose inline verification handles it (and produces
        the canonical error for genuinely bad input)."""
        cs = self.cs
        rs = cs.rs
        if vote.height != rs.height or rs.validators is None:
            cs.enqueue_nowait("vote", VoteMessage(vote), peer_id)
            return
        val = (
            rs.validators.get_by_index(vote.validator_index)
            if 0 <= vote.validator_index < rs.validators.size()
            else None
        )
        if val is None or val.address != vote.validator_address:
            cs.enqueue_nowait("vote", VoteMessage(vote), peer_id)
            return
        try:
            fut = self.vote_verifier.submit(
                val.pub_key, vote.sign_bytes(cs.state.chain_id),
                vote.signature,
            )
        except RuntimeError:  # no running loop (sync test harness)
            cs.enqueue_nowait("vote", VoteMessage(vote), peer_id)
            return

        def _done(f: asyncio.Future) -> None:
            ok = False
            try:
                ok = bool(f.result())
            except Exception:
                pass
            if ok:
                cs.enqueue_nowait("vote", VoteMessage(vote), peer_id)
            else:
                _log.error(
                    "dropping vote with invalid signature",
                    height=vote.height,
                    round=vote.round,
                    peer=peer_id[:12],
                )

        fut.add_done_callback(_done)

    def _on_event(self, e) -> None:
        if e.type_ == ev.EVENT_NEW_ROUND_STEP:
            self._announce_step()

    def _announce_step(self) -> None:
        if self.switch is None or self.wait_sync:
            return
        rs = self.cs.rs
        self.switch.broadcast(
            STATE_CHANNEL,
            encode_new_round_step(rs.height, rs.round, int(rs.step)),
        )

    # --- peers --------------------------------------------------------

    def add_peer(self, peer) -> None:
        peer.set("prs", PeerRoundState())
        rs = self.cs.rs
        if not self.wait_sync:
            peer.try_send(
                STATE_CHANNEL,
                encode_new_round_step(rs.height, rs.round, int(rs.step)),
            )
        self._gossip_tasks[peer.peer_id] = asyncio.create_task(
            self._gossip_routine(peer)
        )

    def remove_peer(self, peer, reason) -> None:
        t = self._gossip_tasks.pop(peer.peer_id, None)
        if t:
            t.cancel()

    # --- the per-peer gossip routine ----------------------------------

    async def _gossip_routine(self, peer) -> None:
        sent_at: Dict[tuple, float] = {}
        cursor = PeerVoteCursor()
        sleep_s = getattr(self.cs.config, "peer_gossip_sleep_s", 0.1)
        try:
            while True:
                await asyncio.sleep(sleep_s)
                if self.wait_sync:
                    continue
                prs: PeerRoundState = peer.get("prs")
                rs = self.cs.rs
                now = time.monotonic()

                def due(key, after=RETRANSMIT_AFTER_S) -> bool:
                    return now - sent_at.get(key, 0.0) > after

                # keep the PEER's view of US fresh (STEP_REANNOUNCE_S
                # above): runs even while we are behind or the peer
                # never announced — a behind node correcting its
                # peers' stale view is exactly what re-aims their
                # catch-up at the right height
                if due(("nrs",), STEP_REANNOUNCE_S):
                    sent_at[("nrs",)] = now
                    peer.try_send(
                        STATE_CHANNEL,
                        encode_new_round_step(
                            rs.height, rs.round, int(rs.step)
                        ),
                    )
                if prs is None or prs.height == 0:
                    continue

                if prs.height < rs.height:
                    # catch-up: ship whole committed blocks, repeating
                    # (paced) until the peer's NewRoundStep advances
                    ckey = ("cb", prs.height)
                    if prs.height <= self.block_store.height() and due(
                        ckey, CATCHUP_RETRANSMIT_S
                    ):
                        block = self.block_store.load_block(prs.height)
                        commit = self.block_store.load_seen_commit(
                            prs.height
                        ) or self.block_store.load_block_commit(prs.height)
                        if block is not None and commit is not None:
                            sent_at[ckey] = now
                            await peer.send(
                                DATA_CHANNEL,
                                self.switch.stamp_msg(
                                    DATA_CHANNEL,
                                    encode_commit_block(
                                        block,
                                        commit,
                                        self.block_store
                                        .load_extended_commit(prs.height),
                                    ),
                                    "commit_block",
                                    height=prs.height,
                                    peer=peer.peer_id,
                                ),
                            )
                    continue
                if prs.height > rs.height:
                    continue  # we're behind; their catch-up feeds us

                # data: proposal + parts for the current round
                if rs.proposal is not None and not prs.proposal_seen:
                    key = ("prop", rs.height, rs.round)
                    if due(key):
                        peer.try_send(
                            DATA_CHANNEL,
                            self.switch.stamp_msg(
                                DATA_CHANNEL,
                                encode_proposal_msg(rs.proposal),
                                "proposal",
                                height=rs.height, round_=rs.round,
                                peer=peer.peer_id,
                            ),
                        )
                        sent_at[key] = now
                if rs.proposal_block_parts is not None:
                    sent_parts = 0
                    for part in rs.proposal_block_parts.parts:
                        if part is None:
                            continue
                        pkey = (rs.height, rs.round, part.index)
                        if pkey in prs.has_parts:
                            continue
                        if not due(("part",) + pkey):
                            continue
                        peer.try_send(
                            DATA_CHANNEL,
                            self.switch.stamp_msg(
                                DATA_CHANNEL,
                                encode_block_part_msg(
                                    rs.height, rs.round, part
                                ),
                                "block_part",
                                height=rs.height, round_=rs.round,
                                peer=peer.peer_id,
                            ),
                        )
                        sent_at[("part",) + pkey] = now
                        sent_parts += 1
                        if sent_parts >= MAX_GOSSIP_PARTS_PER_TICK:
                            break

                # votes: incremental cursor over each source's
                # append-ordered vote_log — O(new + unacked) per
                # tick, not a full O(validators) rescan
                if cursor.height != rs.height:
                    cursor.reset(rs.height)
                cursor.ingest(rs, prs)
                for vote in cursor.due_votes(
                    prs, now, MAX_GOSSIP_VOTES_PER_TICK
                ):
                    peer.try_send(
                        VOTE_CHANNEL,
                        self.switch.stamp_msg(
                            VOTE_CHANNEL, encode_vote_msg(vote), "vote",
                            height=vote.height, round_=vote.round,
                            peer=peer.peer_id,
                        ),
                    )
                if len(sent_at) > 50_000:
                    sent_at.clear()
        except asyncio.CancelledError:
            raise
        except Exception:
            traceback.print_exc()

    # --- inbound ------------------------------------------------------

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        if not msg:
            return
        mtype = msg[0]
        body = msg[1:]
        prs: PeerRoundState = peer.get("prs") or PeerRoundState()
        if mtype == MSG_NEW_ROUND_STEP:
            h, r, s = struct.unpack(">qiB", body)
            if h != prs.height:
                prs.has_votes.clear()
                prs.has_parts.clear()
                prs.proposal_seen = False
            elif r != prs.round:
                prs.proposal_seen = False
            prs.height, prs.round, prs.step = h, r, s
            peer.set("prs", prs)
        elif mtype == MSG_HAS_VOTE:
            h, r, t, i = struct.unpack(">qiBi", body)
            prs.has_votes.add((h, r, t, i))
        elif mtype == MSG_HAS_PART:
            h, r, i = struct.unpack(">qii", body)
            prs.has_parts.add((h, r, i))
        elif self.wait_sync:
            return  # ignore consensus traffic until synced
        elif mtype == MSG_PROPOSAL:
            prop = codec.decode_proposal(body)
            if prop.height == prs.height:
                prs.proposal_seen = True
            self.cs.enqueue_nowait(
                "proposal", ProposalMessage(prop), peer.peer_id
            )
        elif mtype == MSG_BLOCK_PART:
            m = proto.parse(body)
            height = proto.get1(m, 1, 0)
            round_ = proto.get1(m, 2, 1) - 1
            part = _decode_part(proto.get1(m, 3, b""))
            # the sender obviously has it; ack so it stops resending
            prs.has_parts.add((height, round_, part.index))
            peer.try_send(
                STATE_CHANNEL, encode_has_part(height, round_, part.index)
            )
            self.cs.enqueue_nowait(
                "block_part",
                BlockPartMessage(height, round_, part),
                peer.peer_id,
            )
        elif mtype == MSG_VOTE:
            vote = codec.decode_vote(body)
            prs.has_votes.add(_vote_key(vote))
            peer.try_send(STATE_CHANNEL, encode_has_vote(*_vote_key(vote)))
            self._submit_vote(vote, peer.peer_id)
        elif mtype == MSG_COMMIT_BLOCK:
            m = proto.parse(body)
            block = codec.decode_block(proto.get1(m, 1, b""))
            commit = codec.decode_commit(proto.get1(m, 2, b""))
            ec_bytes = proto.get1(m, 3, b"") or None
            self.cs.enqueue_nowait(
                "commit_block",
                CommitBlockMessage(block, commit, ec_bytes),
                peer.peer_id,
            )
        else:
            raise ValueError(f"unknown consensus msg type {mtype}")
