"""ABCI: the application interface (reference abci/types/application.go:9-38).

All 16 baseline methods plus the fork's app-side-mempool extensions
(InsertTx/ReapTxs, reference abci/types/application.go:16-17).
Requests/responses are plain dataclasses; the process-boundary codec
(socket server/client) frames them with the same proto writer used
everywhere else.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

CODE_TYPE_OK = 0


@dataclass
class ValidatorUpdate:
    pub_key_type: str
    pub_key_bytes: bytes
    power: int


@dataclass
class EventAttribute:
    key: str
    value: str
    index: bool = True


@dataclass
class Event:
    type_: str
    # EventAttribute or bare (key, value, index) tuples — use attr_kvi
    attributes: List = field(default_factory=list)


def attr_kvi(a) -> tuple:
    """(key, value, index) from an EventAttribute or tuple."""
    if isinstance(a, EventAttribute):
        return a.key, a.value, a.index
    k, v = a[0], a[1]
    idx = a[2] if len(a) > 2 else True
    if isinstance(k, bytes):
        k = k.decode()
    if isinstance(v, bytes):
        v = v.decode()
    return k, v, bool(idx)


@dataclass
class ExecTxResult:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        from ..utils import proto

        return (
            proto.field_varint(1, self.code)
            + proto.field_bytes(2, self.data)
            + proto.field_varint(5, self.gas_wanted)
            + proto.field_varint(6, self.gas_used)
            + proto.field_string(8, self.codespace)
        )


BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3

MISBEHAVIOR_DUPLICATE_VOTE = 1
MISBEHAVIOR_LIGHT_CLIENT_ATTACK = 2


@dataclass
class VoteInfo:
    """One validator's participation in the decided commit (reference
    abci/types.proto VoteInfo): apps use it for reward distribution."""

    validator_address: bytes = b""
    power: int = 0
    block_id_flag: int = BLOCK_ID_FLAG_ABSENT


@dataclass
class CommitInfo:
    round: int = 0
    votes: List[VoteInfo] = field(default_factory=list)


@dataclass
class ExtendedVoteInfo:
    """VoteInfo plus the validator's vote extension (reference
    abci/types.proto ExtendedVoteInfo — PrepareProposal's
    local_last_commit when ABCI vote extensions are enabled)."""

    validator_address: bytes = b""
    power: int = 0
    block_id_flag: int = 0
    vote_extension: bytes = b""
    extension_signature: bytes = b""


@dataclass
class ExtendedCommitInfo:
    round: int = 0
    votes: List[ExtendedVoteInfo] = field(default_factory=list)


@dataclass
class Misbehavior:
    """Evidence of validator misbehavior handed to the app for slashing
    (reference abci/types.proto Misbehavior)."""

    type_: int = MISBEHAVIOR_DUPLICATE_VOTE
    validator_address: bytes = b""
    validator_power: int = 0
    height: int = 0
    time_ns: int = 0
    total_voting_power: int = 0


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0
    abci_version: str = ""


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class RequestInitChain:
    time_ns: int = 0
    chain_id: str = ""
    consensus_params: Optional[object] = None
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 1


@dataclass
class ResponseInitChain:
    consensus_params: Optional[object] = None
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


CHECK_TX_TYPE_NEW = 0
CHECK_TX_TYPE_RECHECK = 1


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type_: int = CHECK_TX_TYPE_NEW


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    key: bytes = b""
    value: bytes = b""
    height: int = 0
    # encoded crypto/merkle proof-op chain (empty = no proof); light
    # clients verify it against the light-verified AppHash of height+1
    proof_ops: bytes = b""


@dataclass
class RequestPrepareProposal:
    max_tx_bytes: int = 0
    txs: List[bytes] = field(default_factory=list)
    local_last_commit: Optional[object] = None
    misbehavior: list = field(default_factory=list)
    height: int = 0
    time_ns: int = 0
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class ResponsePrepareProposal:
    txs: List[bytes] = field(default_factory=list)


PROCESS_PROPOSAL_ACCEPT = 1
PROCESS_PROPOSAL_REJECT = 2


@dataclass
class RequestProcessProposal:
    txs: List[bytes] = field(default_factory=list)
    proposed_last_commit: Optional[object] = None
    misbehavior: list = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    time_ns: int = 0
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class ResponseProcessProposal:
    status: int = PROCESS_PROPOSAL_ACCEPT

    def is_accepted(self) -> bool:
        return self.status == PROCESS_PROPOSAL_ACCEPT


@dataclass
class RequestExtendVote:
    hash: bytes = b""
    height: int = 0
    round: int = 0
    time_ns: int = 0


@dataclass
class ResponseExtendVote:
    vote_extension: bytes = b""


VERIFY_VOTE_EXT_ACCEPT = 1
VERIFY_VOTE_EXT_REJECT = 2


@dataclass
class RequestVerifyVoteExtension:
    hash: bytes = b""
    validator_address: bytes = b""
    height: int = 0
    vote_extension: bytes = b""


@dataclass
class ResponseVerifyVoteExtension:
    status: int = VERIFY_VOTE_EXT_ACCEPT

    def is_accepted(self) -> bool:
        return self.status == VERIFY_VOTE_EXT_ACCEPT


@dataclass
class RequestFinalizeBlock:
    txs: List[bytes] = field(default_factory=list)
    decided_last_commit: Optional[object] = None
    misbehavior: list = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    time_ns: int = 0
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class ResponseFinalizeBlock:
    events: List[Event] = field(default_factory=list)
    tx_results: List[ExecTxResult] = field(default_factory=list)
    validator_updates: List[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: Optional[object] = None
    app_hash: bytes = b""


@dataclass
class ResponseCommit:
    retain_height: int = 0


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5

APPLY_CHUNK_ACCEPT = 1
APPLY_CHUNK_ABORT = 2
APPLY_CHUNK_RETRY = 3
APPLY_CHUNK_RETRY_SNAPSHOT = 4
APPLY_CHUNK_REJECT_SNAPSHOT = 5


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_ACCEPT


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_CHUNK_ACCEPT
    refetch_chunks: List[int] = field(default_factory=list)
    reject_senders: List[str] = field(default_factory=list)


class Application:
    """The 16-method replicated-application interface + fork extensions.

    Default implementations are accept-everything no-ops so apps override
    only what they need (mirrors abci/types BaseApplication)."""

    # --- info/query connection ---
    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery()

    def echo(self, msg: str) -> str:
        return msg

    # --- mempool connection ---
    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx()

    # fork: batched CheckTx for the mempool ingest plane. The default
    # is the per-tx loop, so every app supports the batch call and
    # overriding it is purely an optimization (one VM entry / one DB
    # snapshot per batch instead of per tx).
    def check_tx_batch(
        self, reqs: List[RequestCheckTx]
    ) -> List[ResponseCheckTx]:
        return [self.check_tx(r) for r in reqs]

    # fork: app-side mempool (abci/types/application.go:16-17)
    def insert_tx(self, tx: bytes) -> bool:
        raise NotImplementedError("app-side mempool not supported")

    def reap_txs(self, max_bytes: int, max_gas: int) -> List[bytes]:
        raise NotImplementedError("app-side mempool not supported")

    # --- consensus connection ---
    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def prepare_proposal(
        self, req: RequestPrepareProposal
    ) -> ResponsePrepareProposal:
        # default: take txs as-is within the byte budget
        out, total = [], 0
        for tx in req.txs:
            if total + len(tx) > req.max_tx_bytes:
                break
            out.append(tx)
            total += len(tx)
        return ResponsePrepareProposal(txs=out)

    def process_proposal(
        self, req: RequestProcessProposal
    ) -> ResponseProcessProposal:
        return ResponseProcessProposal()

    def extend_vote(self, req: RequestExtendVote) -> ResponseExtendVote:
        return ResponseExtendVote()

    def verify_vote_extension(
        self, req: RequestVerifyVoteExtension
    ) -> ResponseVerifyVoteExtension:
        return ResponseVerifyVoteExtension()

    def finalize_block(
        self, req: RequestFinalizeBlock
    ) -> ResponseFinalizeBlock:
        return ResponseFinalizeBlock(
            tx_results=[ExecTxResult() for _ in req.txs]
        )

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    # --- snapshot connection ---
    def list_snapshots(self) -> List[Snapshot]:
        return []

    def offer_snapshot(self, snapshot: Snapshot, app_hash: bytes):
        return ResponseOfferSnapshot(result=OFFER_SNAPSHOT_REJECT)

    def load_snapshot_chunk(
        self, height: int, format_: int, chunk: int
    ) -> bytes:
        return b""

    def apply_snapshot_chunk(
        self, index: int, chunk: bytes, sender: str
    ) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk(result=APPLY_CHUNK_ABORT)
