"""ABCI socket + gRPC clients: connect a node to an out-of-process app.

The socket client mirrors the reference's pipelined request model
(abci/client/socket_client.go): requests are written immediately under
a send lock; a dedicated reader thread matches responses FIFO to
pending futures, so CheckTx can pipeline while consensus calls block
on their own future. ``check_tx_async`` returns a Future like the
reference's async callback path (mempool/clist_mempool.go:223-354).

Same client interface as abci.client.LocalClient, so
``proxy``/``AppConns`` code is transport-agnostic (reference
proxy/multi_app_conn.go spawning 4 connections per app).
"""

from __future__ import annotations

import socket
import threading
from collections import deque
from concurrent.futures import Future
from typing import List, Optional

from ..utils import proto
from . import codec
from . import types as abci
from .client import AppConns
from .server import parse_addr


class SocketClient:
    def __init__(self, addr: str, connect_timeout: float = 10.0):
        self.addr = addr
        scheme, target = parse_addr(addr)
        if scheme == "unix":
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(connect_timeout)
            self._sock.connect(target)
        else:
            self._sock = socket.create_connection(
                target, timeout=connect_timeout
            )
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._pending: "deque[tuple[int, Future]]" = deque()
        self._plock = threading.Lock()
        self._err: Optional[BaseException] = None
        self._closed = False
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name=f"abci-read {addr}"
        )
        self._reader.start()

    # --- transport ----------------------------------------------------

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("abci server closed connection")
            buf += chunk
        return buf

    def _read_frame(self) -> bytes:
        lead = b""
        while True:
            b = self._read_exact(1)
            lead += b
            if not b[0] & 0x80:
                break
            if len(lead) > 10:
                raise ValueError("frame varint too long")
        ln, _ = proto.read_varint(lead, 0)
        if ln < 0 or ln > 64 * 1024 * 1024:
            raise ValueError(f"bad frame length {ln}")
        return self._read_exact(ln)

    def _read_loop(self) -> None:
        try:
            while True:
                frame = self._read_frame()
                kind, resp = None, None
                err = None
                try:
                    kind, resp = codec.decode_response(frame)
                except Exception as e:
                    err = e
                with self._plock:
                    if not self._pending:
                        continue  # unsolicited; drop
                    want, fut = self._pending.popleft()
                if err is not None:
                    fut.set_exception(err)
                elif kind != want:
                    fut.set_exception(
                        RuntimeError(
                            f"abci response kind {kind} != request {want}"
                        )
                    )
                else:
                    fut.set_result(resp)
        except BaseException as e:
            self._err = e
            with self._plock:
                pending, self._pending = list(self._pending), deque()
            for _, fut in pending:
                if not fut.done():
                    fut.set_exception(
                        ConnectionError(f"abci connection lost: {e}")
                    )

    def _send(self, kind: int, req) -> Future:
        if self._err is not None and not self._closed:
            raise ConnectionError(f"abci connection lost: {self._err}")
        data = proto.delimited(codec.encode_request(kind, req))
        fut: Future = Future()
        entry = (kind, fut)
        with self._wlock:
            with self._plock:
                self._pending.append(entry)
            try:
                self._sock.sendall(data)
            except BaseException:
                # a stale entry would desync the FIFO response matching
                with self._plock:
                    try:
                        self._pending.remove(entry)
                    except ValueError:
                        pass
                raise
            # the reader thread may have drained _pending (connection
            # death) between our first _err check and the append; an
            # entry added after the drain would hang its caller forever
            with self._plock:
                if self._err is not None and not fut.done():
                    try:
                        self._pending.remove(entry)
                    except ValueError:
                        pass
                    fut.set_exception(
                        ConnectionError(
                            f"abci connection lost: {self._err}"
                        )
                    )
        return fut

    def _call(self, kind: int, req=None):
        return self._send(kind, req).result()

    # --- client interface (matches LocalClient) -----------------------

    def echo(self, msg: str) -> str:
        return self._call(codec.ECHO, msg)

    def flush(self) -> None:
        self._call(codec.FLUSH)

    def info(self, req):
        return self._call(codec.INFO, req)

    def query(self, req):
        return self._call(codec.QUERY, req)

    def init_chain(self, req):
        return self._call(codec.INIT_CHAIN, req)

    def prepare_proposal(self, req):
        return self._call(codec.PREPARE_PROPOSAL, req)

    def process_proposal(self, req):
        return self._call(codec.PROCESS_PROPOSAL, req)

    def extend_vote(self, req):
        return self._call(codec.EXTEND_VOTE, req)

    def verify_vote_extension(self, req):
        return self._call(codec.VERIFY_VOTE_EXTENSION, req)

    def finalize_block(self, req):
        return self._call(codec.FINALIZE_BLOCK, req)

    def commit(self):
        return self._call(codec.COMMIT, None)

    def check_tx(self, req):
        return self._call(codec.CHECK_TX, req)

    def check_tx_async(self, req) -> Future:
        return self._send(codec.CHECK_TX, req)

    def check_tx_batch(self, reqs):
        """Batched CheckTx over the socket: pipeline every request
        before waiting on any response, so the process boundary costs
        one round-trip per BATCH instead of per tx (the wire protocol
        is unchanged — FIFO request/response matching does the rest)."""
        futs = [self._send(codec.CHECK_TX, r) for r in reqs]
        return [f.result() for f in futs]

    def insert_tx(self, tx: bytes) -> bool:
        return self._call(codec.INSERT_TX, tx)

    def reap_txs(self, max_bytes: int, max_gas: int) -> List[bytes]:
        return self._call(codec.REAP_TXS, (max_bytes, max_gas))

    def list_snapshots(self):
        return self._call(codec.LIST_SNAPSHOTS, None)

    def offer_snapshot(self, snapshot, app_hash):
        return self._call(codec.OFFER_SNAPSHOT, (snapshot, app_hash))

    def load_snapshot_chunk(self, height, format_, chunk) -> bytes:
        return self._call(
            codec.LOAD_SNAPSHOT_CHUNK, (height, format_, chunk)
        )

    def apply_snapshot_chunk(self, index, chunk, sender):
        return self._call(
            codec.APPLY_SNAPSHOT_CHUNK, (index, chunk, sender)
        )


class GRPCClient:
    """Same surface over gRPC (reference abci/client/grpc_client.go);
    gRPC handles its own multiplexing so one channel serves all 4
    logical connections."""

    def __init__(self, addr: str):
        import grpc

        from .server import GRPC_METHOD

        scheme, target = parse_addr(addr)
        if scheme == "unix":
            self._chan = grpc.insecure_channel(f"unix:{target}")
        else:
            self._chan = grpc.insecure_channel(f"{target[0]}:{target[1]}")
        self._callable = self._chan.unary_unary(
            GRPC_METHOD,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )

    def close(self) -> None:
        self._chan.close()

    @staticmethod
    def _decode(kind: int, raw: bytes):
        got, resp = codec.decode_response(raw)
        if got != kind:
            raise RuntimeError(
                f"abci response kind {got} != request {kind}"
            )
        return resp

    def _call(self, kind: int, req=None):
        raw = self._callable(codec.encode_request(kind, req))
        return self._decode(kind, raw)

    def echo(self, msg: str) -> str:
        return self._call(codec.ECHO, msg)

    def info(self, req):
        return self._call(codec.INFO, req)

    def query(self, req):
        return self._call(codec.QUERY, req)

    def init_chain(self, req):
        return self._call(codec.INIT_CHAIN, req)

    def prepare_proposal(self, req):
        return self._call(codec.PREPARE_PROPOSAL, req)

    def process_proposal(self, req):
        return self._call(codec.PROCESS_PROPOSAL, req)

    def extend_vote(self, req):
        return self._call(codec.EXTEND_VOTE, req)

    def verify_vote_extension(self, req):
        return self._call(codec.VERIFY_VOTE_EXTENSION, req)

    def finalize_block(self, req):
        return self._call(codec.FINALIZE_BLOCK, req)

    def commit(self):
        return self._call(codec.COMMIT, None)

    def check_tx(self, req):
        return self._call(codec.CHECK_TX, req)

    def check_tx_async(self, req) -> Future:
        """Pipelined CheckTx: the grpc future API keeps the caller (the
        node's event loop) off the round-trip, matching SocketClient's
        async semantics."""
        fut: Future = Future()
        try:
            rpc = self._callable.future(
                codec.encode_request(codec.CHECK_TX, req)
            )
        except Exception:
            # channel impls without the future API degrade to blocking
            try:
                fut.set_result(self.check_tx(req))
            except Exception as e:
                fut.set_exception(e)
            return fut

        def _done(f):
            try:
                fut.set_result(self._decode(codec.CHECK_TX, f.result()))
            except Exception as e:
                fut.set_exception(e)

        rpc.add_done_callback(_done)
        return fut

    def insert_tx(self, tx: bytes) -> bool:
        return self._call(codec.INSERT_TX, tx)

    def reap_txs(self, max_bytes: int, max_gas: int) -> List[bytes]:
        return self._call(codec.REAP_TXS, (max_bytes, max_gas))

    def list_snapshots(self):
        return self._call(codec.LIST_SNAPSHOTS, None)

    def offer_snapshot(self, snapshot, app_hash):
        return self._call(codec.OFFER_SNAPSHOT, (snapshot, app_hash))

    def load_snapshot_chunk(self, height, format_, chunk) -> bytes:
        return self._call(
            codec.LOAD_SNAPSHOT_CHUNK, (height, format_, chunk)
        )

    def apply_snapshot_chunk(self, index, chunk, sender):
        return self._call(
            codec.APPLY_SNAPSHOT_CHUNK, (index, chunk, sender)
        )


def connect_app_conns(addr: str, transport: str = "socket") -> AppConns:
    """The reference's proxy.NewMultiAppConn for remote apps: 4 named
    connections (consensus/mempool/query/snapshot) each on its own
    socket so a slow consensus call never blocks CheckTx
    (proxy/multi_app_conn.go:21-62)."""
    if transport == "grpc":
        c = GRPCClient(addr)  # grpc multiplexes internally
        return AppConns(c)
    return AppConns(
        SocketClient(addr),
        mempool=SocketClient(addr),
        query=SocketClient(addr),
        snapshot=SocketClient(addr),
    )
