"""ABCI process-boundary wire codec.

The reference frames ABCI requests/responses as varint-length-delimited
protobuf messages with a ``oneof`` discriminator
(abci/types/messages.go, abci/client/socket_client.go:118-160). This
codec does the same with the in-repo proto writer (utils/proto): a
Request/Response envelope whose field number selects the method, with
each payload a nested message. Self-consistent wire format — both ends
are this codec (socket server/client, grpc server/client).
"""

from __future__ import annotations

from typing import List, Tuple

from ..utils import proto
from . import types as abci

# envelope field numbers (match reference Request/Response oneof tags
# where they exist: abci/types.proto Request)
ECHO = 1
FLUSH = 2
INFO = 3
INIT_CHAIN = 5
QUERY = 6
CHECK_TX = 8
COMMIT = 11
LIST_SNAPSHOTS = 12
OFFER_SNAPSHOT = 13
LOAD_SNAPSHOT_CHUNK = 14
APPLY_SNAPSHOT_CHUNK = 15
PREPARE_PROPOSAL = 16
PROCESS_PROPOSAL = 17
EXTEND_VOTE = 18
VERIFY_VOTE_EXTENSION = 19
FINALIZE_BLOCK = 20
# fork extensions (abci/types/application.go:16-17 InsertTx/ReapTxs)
INSERT_TX = 21
REAP_TXS = 22
EXCEPTION = 63


# --- shared sub-messages ------------------------------------------------


def enc_event(e: abci.Event) -> bytes:
    out = proto.field_string(1, e.type_)
    for a in e.attributes:
        k, v, idx = abci.attr_kvi(a)
        out += proto.field_message(
            2,
            proto.field_string(1, k)
            + proto.field_string(2, v)
            + proto.field_varint(3, 1 if idx else 0),
        )
    return out


def dec_event(raw: bytes) -> abci.Event:
    m = proto.parse(raw)
    attrs = []
    for am in m.get(2, []):
        a = proto.parse(am)
        attrs.append(
            abci.EventAttribute(
                key=proto.get1(a, 1, b"").decode(),
                value=proto.get1(a, 2, b"").decode(),
                index=bool(proto.get1(a, 3, 0)),
            )
        )
    return abci.Event(
        type_=proto.get1(m, 1, b"").decode(), attributes=attrs
    )


def enc_tx_result(r: abci.ExecTxResult) -> bytes:
    return (
        proto.field_varint(1, r.code)
        + proto.field_bytes(2, r.data)
        + proto.field_string(3, r.log)
        + proto.field_string(4, r.info)
        + proto.field_varint(5, r.gas_wanted)
        + proto.field_varint(6, r.gas_used)
        + b"".join(proto.field_message(7, enc_event(e)) for e in r.events)
        + proto.field_string(8, r.codespace)
    )


def dec_tx_result(raw: bytes) -> abci.ExecTxResult:
    m = proto.parse(raw)
    return abci.ExecTxResult(
        code=proto.get1(m, 1, 0),
        data=proto.get1(m, 2, b""),
        log=proto.get1(m, 3, b"").decode(),
        info=proto.get1(m, 4, b"").decode(),
        gas_wanted=proto.get1(m, 5, 0),
        gas_used=proto.get1(m, 6, 0),
        events=[dec_event(e) for e in m.get(7, [])],
        codespace=proto.get1(m, 8, b"").decode(),
    )


def enc_validator_update(v: abci.ValidatorUpdate) -> bytes:
    return (
        proto.field_string(1, v.pub_key_type)
        + proto.field_bytes(2, v.pub_key_bytes)
        + proto.field_varint(3, v.power)
    )


def dec_validator_update(raw: bytes) -> abci.ValidatorUpdate:
    m = proto.parse(raw)
    return abci.ValidatorUpdate(
        pub_key_type=proto.get1(m, 1, b"").decode(),
        pub_key_bytes=proto.get1(m, 2, b""),
        power=proto.get1(m, 3, 0),
    )


def enc_commit_info(ci) -> bytes:
    if ci is None:
        return None
    out = proto.field_varint(1, ci.round)
    for v in ci.votes:
        out += proto.field_message(
            2,
            proto.field_bytes(1, v.validator_address)
            + proto.field_varint(2, v.power)
            + proto.field_varint(3, v.block_id_flag),
        )
    return out  # may be b"": field_message still emits it when not None


def dec_commit_info(raw) -> abci.CommitInfo:
    if raw is None:
        return None
    m = proto.parse(raw)
    votes = []
    for vm in m.get(2, []):
        v = proto.parse(vm)
        votes.append(
            abci.VoteInfo(
                validator_address=proto.get1(v, 1, b""),
                power=proto.get1(v, 2, 0),
                block_id_flag=proto.get1(v, 3, abci.BLOCK_ID_FLAG_ABSENT),
            )
        )
    return abci.CommitInfo(round=proto.get1(m, 1, 0), votes=votes)


def enc_misbehavior(mb: abci.Misbehavior) -> bytes:
    return (
        proto.field_varint(1, mb.type_)
        + proto.field_bytes(2, mb.validator_address)
        + proto.field_varint(3, mb.validator_power)
        + proto.field_varint(4, mb.height)
        + proto.field_varint(5, mb.time_ns)
        + proto.field_varint(6, mb.total_voting_power)
    )


def dec_misbehavior(raw: bytes) -> abci.Misbehavior:
    m = proto.parse(raw)
    return abci.Misbehavior(
        type_=proto.get1(m, 1, 0),
        validator_address=proto.get1(m, 2, b""),
        validator_power=proto.get1(m, 3, 0),
        height=proto.get1(m, 4, 0),
        time_ns=proto.get1(m, 5, 0),
        total_voting_power=proto.get1(m, 6, 0),
    )


def _enc_params(p) -> bytes:
    return None if p is None else p.encode()


def _dec_params(raw):
    if raw is None:
        return None
    from ..state.state_types import ConsensusParams

    return ConsensusParams.decode(raw)


def enc_snapshot(s: abci.Snapshot) -> bytes:
    return (
        proto.field_varint(1, s.height)
        + proto.field_varint(2, s.format)
        + proto.field_varint(3, s.chunks)
        + proto.field_bytes(4, s.hash)
        + proto.field_bytes(5, s.metadata)
    )


def dec_snapshot(raw: bytes) -> abci.Snapshot:
    m = proto.parse(raw)
    return abci.Snapshot(
        height=proto.get1(m, 1, 0),
        format=proto.get1(m, 2, 0),
        chunks=proto.get1(m, 3, 0),
        hash=proto.get1(m, 4, b""),
        metadata=proto.get1(m, 5, b""),
    )


# --- requests -----------------------------------------------------------


def encode_request(kind: int, req) -> bytes:
    """Envelope a request; ``req`` is the dataclass for ``kind`` (or a
    tuple for the primitive-arg methods)."""
    if kind == ECHO:
        body = proto.field_string(1, req)
    elif kind in (FLUSH, COMMIT, LIST_SNAPSHOTS):
        body = b""
    elif kind == INFO:
        body = (
            proto.field_string(1, req.version)
            + proto.field_varint(2, req.block_version)
            + proto.field_varint(3, req.p2p_version)
            + proto.field_string(4, req.abci_version)
        )
    elif kind == INIT_CHAIN:
        body = (
            proto.field_varint(1, req.time_ns)
            + proto.field_string(2, req.chain_id)
            + proto.field_message(3, _enc_params(req.consensus_params))
            + b"".join(
                proto.field_message(4, enc_validator_update(v))
                for v in req.validators
            )
            + proto.field_bytes(5, req.app_state_bytes)
            + proto.field_varint(6, req.initial_height)
        )
    elif kind == QUERY:
        body = (
            proto.field_bytes(1, req.data)
            + proto.field_string(2, req.path)
            + proto.field_varint(3, req.height)
            + proto.field_varint(4, 1 if req.prove else 0)
        )
    elif kind == CHECK_TX:
        body = proto.field_bytes(1, req.tx) + proto.field_varint(
            3, req.type_
        )
    elif kind == OFFER_SNAPSHOT:
        snap, app_hash = req
        body = proto.field_message(1, enc_snapshot(snap)) + proto.field_bytes(
            2, app_hash
        )
    elif kind == LOAD_SNAPSHOT_CHUNK:
        h, f, c = req
        body = (
            proto.field_varint(1, h)
            + proto.field_varint(2, f)
            + proto.field_varint(3, c)
        )
    elif kind == APPLY_SNAPSHOT_CHUNK:
        idx, chunk, sender = req
        body = (
            proto.field_varint(1, idx)
            + proto.field_bytes(2, chunk)
            + proto.field_string(3, sender)
        )
    elif kind == PREPARE_PROPOSAL:
        body = (
            proto.field_varint(1, req.max_tx_bytes)
            + b"".join(proto.field_bytes(2, t) or proto.field_message(2, b"") for t in req.txs)
            + proto.field_message(3, enc_commit_info(req.local_last_commit))
            + b"".join(
                proto.field_message(4, enc_misbehavior(mb))
                for mb in req.misbehavior
            )
            + proto.field_varint(5, req.height)
            + proto.field_varint(6, req.time_ns)
            + proto.field_bytes(7, req.next_validators_hash)
            + proto.field_bytes(8, req.proposer_address)
        )
    elif kind == PROCESS_PROPOSAL:
        body = (
            b"".join(proto.field_bytes(1, t) or proto.field_message(1, b"") for t in req.txs)
            + proto.field_message(2, enc_commit_info(req.proposed_last_commit))
            + b"".join(
                proto.field_message(3, enc_misbehavior(mb))
                for mb in req.misbehavior
            )
            + proto.field_bytes(4, req.hash)
            + proto.field_varint(5, req.height)
            + proto.field_varint(6, req.time_ns)
            + proto.field_bytes(7, req.next_validators_hash)
            + proto.field_bytes(8, req.proposer_address)
        )
    elif kind == EXTEND_VOTE:
        body = (
            proto.field_bytes(1, req.hash)
            + proto.field_varint(2, req.height)
            + proto.field_varint(3, req.round)
            + proto.field_varint(4, req.time_ns)
        )
    elif kind == VERIFY_VOTE_EXTENSION:
        body = (
            proto.field_bytes(1, req.hash)
            + proto.field_bytes(2, req.validator_address)
            + proto.field_varint(3, req.height)
            + proto.field_bytes(4, req.vote_extension)
        )
    elif kind == FINALIZE_BLOCK:
        body = (
            b"".join(proto.field_bytes(1, t) or proto.field_message(1, b"") for t in req.txs)
            + proto.field_message(2, enc_commit_info(req.decided_last_commit))
            + b"".join(
                proto.field_message(3, enc_misbehavior(mb))
                for mb in req.misbehavior
            )
            + proto.field_bytes(4, req.hash)
            + proto.field_varint(5, req.height)
            + proto.field_varint(6, req.time_ns)
            + proto.field_bytes(7, req.next_validators_hash)
            + proto.field_bytes(8, req.proposer_address)
        )
    elif kind == INSERT_TX:
        body = proto.field_bytes(1, req)
    elif kind == REAP_TXS:
        mb, mg = req
        body = proto.field_sfixed64(1, mb) + proto.field_sfixed64(2, mg)
    else:
        raise ValueError(f"unknown request kind {kind}")
    return proto.field_message(kind, body)


def decode_request(raw: bytes) -> Tuple[int, object]:
    env = proto.parse(raw)
    if len(env) != 1:
        raise ValueError("request envelope must have exactly one field")
    kind = next(iter(env))
    m = proto.parse(env[kind][0])
    g = lambda f, d=0: proto.get1(m, f, d)  # noqa: E731
    if kind == ECHO:
        return kind, proto.get1(m, 1, b"").decode()
    if kind in (FLUSH, COMMIT, LIST_SNAPSHOTS):
        return kind, None
    if kind == INFO:
        return kind, abci.RequestInfo(
            version=proto.get1(m, 1, b"").decode(),
            block_version=g(2),
            p2p_version=g(3),
            abci_version=proto.get1(m, 4, b"").decode(),
        )
    if kind == INIT_CHAIN:
        return kind, abci.RequestInitChain(
            time_ns=g(1),
            chain_id=proto.get1(m, 2, b"").decode(),
            consensus_params=_dec_params(proto.get1(m, 3)),
            validators=[dec_validator_update(v) for v in m.get(4, [])],
            app_state_bytes=g(5, b""),
            initial_height=g(6, 1),
        )
    if kind == QUERY:
        return kind, abci.RequestQuery(
            data=g(1, b""),
            path=proto.get1(m, 2, b"").decode(),
            height=g(3),
            prove=bool(g(4)),
        )
    if kind == CHECK_TX:
        return kind, abci.RequestCheckTx(tx=g(1, b""), type_=g(3))
    if kind == OFFER_SNAPSHOT:
        return kind, (dec_snapshot(proto.get1(m, 1, b"")), g(2, b""))
    if kind == LOAD_SNAPSHOT_CHUNK:
        return kind, (g(1), g(2), g(3))
    if kind == APPLY_SNAPSHOT_CHUNK:
        return kind, (g(1), g(2, b""), proto.get1(m, 3, b"").decode())
    if kind == PREPARE_PROPOSAL:
        return kind, abci.RequestPrepareProposal(
            max_tx_bytes=g(1),
            txs=list(m.get(2, [])),
            local_last_commit=dec_commit_info(proto.get1(m, 3)),
            misbehavior=[dec_misbehavior(x) for x in m.get(4, [])],
            height=g(5),
            time_ns=g(6),
            next_validators_hash=g(7, b""),
            proposer_address=g(8, b""),
        )
    if kind == PROCESS_PROPOSAL:
        return kind, abci.RequestProcessProposal(
            txs=list(m.get(1, [])),
            proposed_last_commit=dec_commit_info(proto.get1(m, 2)),
            misbehavior=[dec_misbehavior(x) for x in m.get(3, [])],
            hash=g(4, b""),
            height=g(5),
            time_ns=g(6),
            next_validators_hash=g(7, b""),
            proposer_address=g(8, b""),
        )
    if kind == EXTEND_VOTE:
        return kind, abci.RequestExtendVote(
            hash=g(1, b""), height=g(2), round=g(3), time_ns=g(4)
        )
    if kind == VERIFY_VOTE_EXTENSION:
        return kind, abci.RequestVerifyVoteExtension(
            hash=g(1, b""),
            validator_address=g(2, b""),
            height=g(3),
            vote_extension=g(4, b""),
        )
    if kind == FINALIZE_BLOCK:
        return kind, abci.RequestFinalizeBlock(
            txs=list(m.get(1, [])),
            decided_last_commit=dec_commit_info(proto.get1(m, 2)),
            misbehavior=[dec_misbehavior(x) for x in m.get(3, [])],
            hash=g(4, b""),
            height=g(5),
            time_ns=g(6),
            next_validators_hash=g(7, b""),
            proposer_address=g(8, b""),
        )
    if kind == INSERT_TX:
        return kind, g(1, b"")
    if kind == REAP_TXS:
        return kind, (g(1), g(2))
    raise ValueError(f"unknown request kind {kind}")


# --- responses ----------------------------------------------------------


def encode_response(kind: int, resp) -> bytes:
    if kind == EXCEPTION:
        body = proto.field_string(1, str(resp))
    elif kind == ECHO:
        body = proto.field_string(1, resp)
    elif kind == FLUSH:
        body = b""
    elif kind == INFO:
        body = (
            proto.field_string(1, resp.data)
            + proto.field_string(2, resp.version)
            + proto.field_varint(3, resp.app_version)
            + proto.field_varint(4, resp.last_block_height)
            + proto.field_bytes(5, resp.last_block_app_hash)
        )
    elif kind == INIT_CHAIN:
        body = (
            proto.field_message(1, _enc_params(resp.consensus_params))
            + b"".join(
                proto.field_message(2, enc_validator_update(v))
                for v in resp.validators
            )
            + proto.field_bytes(3, resp.app_hash)
        )
    elif kind == QUERY:
        body = (
            proto.field_varint(1, resp.code)
            + proto.field_string(2, resp.log)
            + proto.field_bytes(3, resp.key)
            + proto.field_bytes(4, resp.value)
            + proto.field_varint(5, resp.height)
            + proto.field_bytes(6, resp.proof_ops)
        )
    elif kind == CHECK_TX:
        body = (
            proto.field_varint(1, resp.code)
            + proto.field_bytes(2, resp.data)
            + proto.field_string(3, resp.log)
            + proto.field_varint(5, resp.gas_wanted)
            + proto.field_string(8, resp.codespace)
        )
    elif kind == COMMIT:
        body = proto.field_varint(3, resp.retain_height)
    elif kind == LIST_SNAPSHOTS:
        body = b"".join(
            proto.field_message(1, enc_snapshot(s)) for s in resp
        )
    elif kind == OFFER_SNAPSHOT:
        body = proto.field_varint(1, resp.result)
    elif kind == LOAD_SNAPSHOT_CHUNK:
        body = proto.field_bytes(1, resp)
    elif kind == APPLY_SNAPSHOT_CHUNK:
        body = (
            proto.field_varint(1, resp.result)
            + b"".join(proto.field_varint(2, c) or proto.tag(2, 0) + b"\x00" for c in resp.refetch_chunks)
            + b"".join(proto.field_string(3, s) for s in resp.reject_senders)
        )
    elif kind == PREPARE_PROPOSAL:
        body = b"".join(proto.field_bytes(1, t) or proto.field_message(1, b"") for t in resp.txs)
    elif kind == PROCESS_PROPOSAL:
        body = proto.field_varint(1, resp.status)
    elif kind == EXTEND_VOTE:
        body = proto.field_bytes(1, resp.vote_extension)
    elif kind == VERIFY_VOTE_EXTENSION:
        body = proto.field_varint(1, resp.status)
    elif kind == FINALIZE_BLOCK:
        body = (
            b"".join(proto.field_message(1, enc_event(e)) for e in resp.events)
            + b"".join(
                proto.field_message(2, enc_tx_result(r))
                for r in resp.tx_results
            )
            + b"".join(
                proto.field_message(3, enc_validator_update(v))
                for v in resp.validator_updates
            )
            + proto.field_message(
                4, _enc_params(resp.consensus_param_updates)
            )
            + proto.field_bytes(5, resp.app_hash)
        )
    elif kind == INSERT_TX:
        body = proto.field_varint(1, 1 if resp else 0)
    elif kind == REAP_TXS:
        body = b"".join(proto.field_bytes(1, t) or proto.field_message(1, b"") for t in resp)
    else:
        raise ValueError(f"unknown response kind {kind}")
    return proto.field_message(kind, body)


def decode_response(raw: bytes) -> Tuple[int, object]:
    env = proto.parse(raw)
    if len(env) != 1:
        raise ValueError("response envelope must have exactly one field")
    kind = next(iter(env))
    m = proto.parse(env[kind][0])
    g = lambda f, d=0: proto.get1(m, f, d)  # noqa: E731
    if kind == EXCEPTION:
        raise RuntimeError(
            "abci exception: " + proto.get1(m, 1, b"").decode()
        )
    if kind == ECHO:
        return kind, proto.get1(m, 1, b"").decode()
    if kind == FLUSH:
        return kind, None
    if kind == INFO:
        return kind, abci.ResponseInfo(
            data=proto.get1(m, 1, b"").decode(),
            version=proto.get1(m, 2, b"").decode(),
            app_version=g(3),
            last_block_height=g(4),
            last_block_app_hash=g(5, b""),
        )
    if kind == INIT_CHAIN:
        return kind, abci.ResponseInitChain(
            consensus_params=_dec_params(proto.get1(m, 1)),
            validators=[dec_validator_update(v) for v in m.get(2, [])],
            app_hash=g(3, b""),
        )
    if kind == QUERY:
        return kind, abci.ResponseQuery(
            code=g(1),
            log=proto.get1(m, 2, b"").decode(),
            key=g(3, b""),
            value=g(4, b""),
            height=g(5),
            proof_ops=g(6, b""),
        )
    if kind == CHECK_TX:
        return kind, abci.ResponseCheckTx(
            code=g(1),
            data=g(2, b""),
            log=proto.get1(m, 3, b"").decode(),
            gas_wanted=g(5),
            codespace=proto.get1(m, 8, b"").decode(),
        )
    if kind == COMMIT:
        return kind, abci.ResponseCommit(retain_height=g(3))
    if kind == LIST_SNAPSHOTS:
        return kind, [dec_snapshot(s) for s in m.get(1, [])]
    if kind == OFFER_SNAPSHOT:
        return kind, abci.ResponseOfferSnapshot(
            result=g(1, abci.OFFER_SNAPSHOT_REJECT)
        )
    if kind == LOAD_SNAPSHOT_CHUNK:
        return kind, g(1, b"")
    if kind == APPLY_SNAPSHOT_CHUNK:
        return kind, abci.ResponseApplySnapshotChunk(
            result=g(1, abci.APPLY_CHUNK_ABORT),
            refetch_chunks=list(m.get(2, [])),
            reject_senders=[s.decode() for s in m.get(3, [])],
        )
    if kind == PREPARE_PROPOSAL:
        return kind, abci.ResponsePrepareProposal(txs=list(m.get(1, [])))
    if kind == PROCESS_PROPOSAL:
        return kind, abci.ResponseProcessProposal(
            status=g(1, abci.PROCESS_PROPOSAL_REJECT)
        )
    if kind == EXTEND_VOTE:
        return kind, abci.ResponseExtendVote(vote_extension=g(1, b""))
    if kind == VERIFY_VOTE_EXTENSION:
        return kind, abci.ResponseVerifyVoteExtension(
            status=g(1, abci.VERIFY_VOTE_EXT_REJECT)
        )
    if kind == FINALIZE_BLOCK:
        return kind, abci.ResponseFinalizeBlock(
            events=[dec_event(e) for e in m.get(1, [])],
            tx_results=[dec_tx_result(r) for r in m.get(2, [])],
            validator_updates=[
                dec_validator_update(v) for v in m.get(3, [])
            ],
            consensus_param_updates=_dec_params(proto.get1(m, 4)),
            app_hash=g(5, b""),
        )
    if kind == INSERT_TX:
        return kind, bool(g(1))
    if kind == REAP_TXS:
        return kind, list(m.get(1, []))
    raise ValueError(f"unknown response kind {kind}")
