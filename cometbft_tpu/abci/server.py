"""ABCI socket + gRPC servers: host an Application out-of-process.

Mirrors the reference's abci/server (socket_server.go: varint-delimited
request/response frames, one serialized request stream per connection;
grpc_server.go: the same surface over gRPC). The app side of the
process boundary — a chain node connects with abci.socket_client /
abci.grpc_client and sees the same AppConns interface as the
in-process local client.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from ..utils import proto
from . import codec
from . import types as abci


def parse_addr(addr: str):
    """'tcp://h:p' | 'unix:///path' -> ('tcp', (h, p)) | ('unix', path)."""
    if addr.startswith("tcp://"):
        hp = addr[len("tcp://") :]
        host, _, port = hp.rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    if addr.startswith("unix://"):
        return "unix", addr[len("unix://") :]
    # bare host:port
    host, _, port = addr.rpartition(":")
    return "tcp", (host or "127.0.0.1", int(port))


def handle_request(app: abci.Application, kind: int, req) -> bytes:
    """Dispatch one decoded request to the Application; returns an
    encoded response (EXCEPTION envelope on error)."""
    try:
        if kind == codec.ECHO:
            return codec.encode_response(kind, app.echo(req))
        if kind == codec.FLUSH:
            return codec.encode_response(kind, None)
        if kind == codec.INFO:
            return codec.encode_response(kind, app.info(req))
        if kind == codec.INIT_CHAIN:
            return codec.encode_response(kind, app.init_chain(req))
        if kind == codec.QUERY:
            return codec.encode_response(kind, app.query(req))
        if kind == codec.CHECK_TX:
            return codec.encode_response(kind, app.check_tx(req))
        if kind == codec.COMMIT:
            return codec.encode_response(kind, app.commit())
        if kind == codec.LIST_SNAPSHOTS:
            return codec.encode_response(kind, app.list_snapshots())
        if kind == codec.OFFER_SNAPSHOT:
            return codec.encode_response(kind, app.offer_snapshot(*req))
        if kind == codec.LOAD_SNAPSHOT_CHUNK:
            return codec.encode_response(
                kind, app.load_snapshot_chunk(*req)
            )
        if kind == codec.APPLY_SNAPSHOT_CHUNK:
            return codec.encode_response(
                kind, app.apply_snapshot_chunk(*req)
            )
        if kind == codec.PREPARE_PROPOSAL:
            return codec.encode_response(kind, app.prepare_proposal(req))
        if kind == codec.PROCESS_PROPOSAL:
            return codec.encode_response(kind, app.process_proposal(req))
        if kind == codec.EXTEND_VOTE:
            return codec.encode_response(kind, app.extend_vote(req))
        if kind == codec.VERIFY_VOTE_EXTENSION:
            return codec.encode_response(
                kind, app.verify_vote_extension(req)
            )
        if kind == codec.FINALIZE_BLOCK:
            return codec.encode_response(kind, app.finalize_block(req))
        if kind == codec.INSERT_TX:
            return codec.encode_response(kind, app.insert_tx(req))
        if kind == codec.REAP_TXS:
            return codec.encode_response(kind, app.reap_txs(*req))
        return codec.encode_response(
            codec.EXCEPTION, f"unknown request kind {kind}"
        )
    except Exception as e:
        return codec.encode_response(codec.EXCEPTION, e)


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    """Read one varint-delimited frame; None on clean EOF."""
    lead = b""
    while True:
        b = await reader.read(1)
        if not b:
            return None if not lead else _trunc()
        lead += b
        if not b[0] & 0x80:
            break
        if len(lead) > 10:
            raise ValueError("frame varint too long")
    ln, _ = proto.read_varint(lead, 0)
    if ln < 0 or ln > 64 * 1024 * 1024:
        raise ValueError(f"bad frame length {ln}")
    return await reader.readexactly(ln)


def _trunc():
    raise ValueError("truncated frame")


class ABCIServer:
    """Asyncio socket server; requests on each connection are handled
    strictly in order (the reference's per-connection serialization,
    abci/server/socket_server.go). The app-level lock serializes across
    connections like the local client's global mutex."""

    def __init__(self, app: abci.Application, addr: str):
        self.app = app
        self.addr = addr
        self._lock = threading.Lock()
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        scheme, target = parse_addr(self.addr)
        if scheme == "unix":
            self._server = await asyncio.start_unix_server(
                self._handle, path=target
            )
        else:
            self._server = await asyncio.start_server(
                self._handle, host=target[0], port=target[1]
            )

    @property
    def listen_addr(self) -> str:
        socks = self._server.sockets
        name = socks[0].getsockname()
        if isinstance(name, tuple):
            return f"tcp://{name[0]}:{name[1]}"
        return f"unix://{name}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def _call(self, kind: int, req) -> bytes:
        with self._lock:
            return handle_request(self.app, kind, req)

    async def _handle(self, reader, writer) -> None:
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                kind, req = codec.decode_request(frame)
                # run the (possibly slow) app call off the event loop
                resp = await asyncio.to_thread(self._call, kind, req)
                writer.write(proto.delimited(resp))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except asyncio.CancelledError:
            raise  # server stop cancels handlers; never swallow it
        except Exception as e:  # malformed frame: report then drop conn
            try:
                writer.write(
                    proto.delimited(
                        codec.encode_response(codec.EXCEPTION, e)
                    )
                )
                await writer.drain()
            except (OSError, RuntimeError):
                pass  # peer already gone / transport torn down
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (OSError, RuntimeError):
                pass  # double-close on a dead transport is fine


GRPC_METHOD = "/cometbft.abci.ABCI/Call"


class GRPCServer:
    """The same ABCI surface over gRPC (reference abci/server/
    grpc_server.go). One unary-unary generic method carries the codec
    envelope; no codegen needed."""

    def __init__(self, app: abci.Application, addr: str):
        self.app = app
        self.addr = addr
        self._server = None
        self._lock = threading.Lock()

    def start(self) -> None:
        import grpc

        def call(request: bytes, context) -> bytes:
            kind, req = codec.decode_request(request)
            with self._lock:
                return handle_request(self.app, kind, req)

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == GRPC_METHOD:
                    return grpc.unary_unary_rpc_method_handler(call)
                return None

        from concurrent.futures import ThreadPoolExecutor

        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=4), handlers=(Handler(),)
        )
        scheme, target = parse_addr(self.addr)
        if scheme == "unix":
            port = self._server.add_insecure_port(f"unix:{target}")
        else:
            port = self._server.add_insecure_port(
                f"{target[0]}:{target[1]}"
            )
        self.port = port
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
