"""ABCI clients: in-process local client (reference abci/client/local_client.go).

The local client wraps an Application with a mutex, preserving the
reference's guarantee that ABCI calls on one connection are serialized.
Async semantics (callback pipelining of the socket client) are provided
by `check_tx_async` returning a future resolved inline — the asyncio
socket client lives in abci/server.py for the process boundary.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Callable, List, Optional

from ..analysis.runtime import sanitized_lock
from . import types as abci


class LocalClient:
    def __init__(self, app: abci.Application, lock: Optional[threading.RLock] = None):
        self.app = app
        # one shared lock across the 4 "connections" mirrors the local
        # client's global mutex in the reference
        self._lock = lock or sanitized_lock(
            threading.RLock(), "abci.app"
        )

    # consensus connection
    def init_chain(self, req):
        with self._lock:
            return self.app.init_chain(req)

    def prepare_proposal(self, req):
        with self._lock:
            return self.app.prepare_proposal(req)

    def process_proposal(self, req):
        with self._lock:
            return self.app.process_proposal(req)

    def extend_vote(self, req):
        with self._lock:
            return self.app.extend_vote(req)

    def verify_vote_extension(self, req):
        with self._lock:
            return self.app.verify_vote_extension(req)

    def finalize_block(self, req):
        with self._lock:
            return self.app.finalize_block(req)

    def commit(self):
        with self._lock:
            return self.app.commit()

    # mempool connection
    def check_tx(self, req):
        with self._lock:
            return self.app.check_tx(req)

    def check_tx_batch(self, reqs: List[abci.RequestCheckTx]):
        """Batched CheckTx: ONE mutex acquisition for the whole batch
        (the per-item lock bounce is most of the local client's cost
        at mempool ingest rates). Apps without the extension get the
        per-tx loop under the same single acquisition."""
        with self._lock:
            fn = getattr(self.app, "check_tx_batch", None)
            if fn is not None:
                return fn(reqs)
            return [self.app.check_tx(r) for r in reqs]

    def check_tx_async(self, req) -> Future:
        f: Future = Future()
        try:
            f.set_result(self.check_tx(req))
        except Exception as e:  # pragma: no cover
            f.set_exception(e)
        return f

    def insert_tx(self, tx: bytes) -> bool:
        with self._lock:
            return self.app.insert_tx(tx)

    def reap_txs(self, max_bytes: int, max_gas: int) -> List[bytes]:
        with self._lock:
            return self.app.reap_txs(max_bytes, max_gas)

    # info connection
    def info(self, req):
        with self._lock:
            return self.app.info(req)

    def query(self, req):
        with self._lock:
            return self.app.query(req)

    def echo(self, msg):
        with self._lock:
            return self.app.echo(msg)

    # snapshot connection
    def list_snapshots(self):
        with self._lock:
            return self.app.list_snapshots()

    def offer_snapshot(self, snapshot, app_hash):
        with self._lock:
            return self.app.offer_snapshot(snapshot, app_hash)

    def load_snapshot_chunk(self, height, format_, chunk):
        with self._lock:
            return self.app.load_snapshot_chunk(height, format_, chunk)

    def apply_snapshot_chunk(self, index, chunk, sender):
        with self._lock:
            return self.app.apply_snapshot_chunk(index, chunk, sender)


class AppConns:
    """Four named logical connections sharing one client (reference
    proxy/multi_app_conn.go:21-62: consensus/mempool/query/snapshot)."""

    def __init__(self, client, mempool=None, query=None, snapshot=None):
        self.consensus = client
        self.mempool = mempool or client
        self.query = query or client
        self.snapshot = snapshot or client

    @classmethod
    def local(cls, app: abci.Application) -> "AppConns":
        return cls(LocalClient(app))
