from .block_store import BlockStore  # noqa: F401
