from .block_store import BlockStore  # noqa: F401
from .retention import RetentionPlane  # noqa: F401
