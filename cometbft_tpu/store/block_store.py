"""Block store: blocks, parts, metas, commits by height (reference store/store.go).

Key layout (all big-endian heights for ordered iteration):
  H:<height>     -> block meta (block id + header, proto)
  P:<height>:<i> -> block part bytes
  C:<height>     -> last commit for height (i.e. commit FOR height, stored
                    under the height it certifies, reference SaveBlock)
  SC:<height>    -> "seen commit" (the commit this node saw for its own
                    last block)
  EC:<height>    -> extended commit (vote extensions)
  BH:<hash>      -> height (lookup by block hash)
  base/height    -> store bounds
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional

from ..analysis.runtime import sanitized_lock
from ..types.block import Block, BlockID, Commit, Header
from ..types.part_set import Part, PartSet
from ..utils import codec, kv, proto


def _hkey(prefix: bytes, h: int) -> bytes:
    return prefix + h.to_bytes(8, "big")


def _commit_bytes(commit: Commit) -> bytes:
    """Wire form, reusing the decode-time memo when present (see
    codec.decode_commit: decoded objects are immutable by convention)."""
    return getattr(commit, "_raw_bytes", None) or codec.encode_commit(commit)


@dataclass
class BlockMeta:
    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int

    def encode(self) -> bytes:
        return (
            proto.field_message(1, self.block_id.encode())
            + proto.field_varint(2, self.block_size)
            + proto.field_message(3, codec.encode_header(self.header))
            + proto.field_varint(4, self.num_txs)
        )

    @classmethod
    def decode(cls, b: bytes) -> "BlockMeta":
        m = proto.parse(b)
        return cls(
            block_id=codec.decode_block_id(proto.get1(m, 1, b"")),
            block_size=proto.get1(m, 2, 0),
            header=codec.decode_header(proto.get1(m, 3, b"")),
            num_txs=proto.get1(m, 4, 0),
        )


class BlockStore:
    def __init__(self, db: kv.KV):
        self.db = db
        self._lock = sanitized_lock(threading.RLock(), "store.block")
        self._base = int.from_bytes(db.get(b"base") or b"\0" * 8, "big")
        self._height = int.from_bytes(db.get(b"height") or b"\0" * 8, "big")

    def base(self) -> int:
        return self._base

    def height(self) -> int:
        return self._height

    def size(self) -> int:
        return 0 if self._height == 0 else self._height - self._base + 1

    # --- save ---------------------------------------------------------

    @staticmethod
    def _block_sets(
        block: Block, part_set: PartSet, seen_commit: Commit
    ) -> List:
        """The per-block KV writes shared by save_block and
        save_block_batch (everything except base/height bookkeeping)."""
        h = block.height
        bid = BlockID(block.hash(), part_set.header)
        meta = BlockMeta(
            block_id=bid,
            block_size=part_set.byte_size,
            header=block.header,
            num_txs=len(block.data.txs),
        )
        sets = [
            (_hkey(b"H:", h), meta.encode()),
            (b"BH:" + block.hash(), h.to_bytes(8, "big")),
            # SC always re-encodes canonically: in the blocksync loop
            # the seen commit is sliced from block h+1's wire bytes,
            # whose canonical-encoding (psh) check only runs one
            # iteration LATER — trusting its decode-time memo here
            # would persist a byzantine peer's non-canonical encoding.
            # C: (below) may reuse the memo: it comes from THIS block,
            # which every save path has already canonicality-checked.
            (_hkey(b"SC:", h), codec.encode_commit(seen_commit)),
        ]
        for i in range(part_set.header.total):
            part = part_set.get_part(i)
            sets.append(
                (
                    _hkey(b"P:", h) + i.to_bytes(4, "big"),
                    _encode_part(part),
                )
            )
        if block.last_commit is not None:
            sets.append(
                (_hkey(b"C:", h - 1), _commit_bytes(block.last_commit))
            )
        return sets

    def save_block(
        self, block: Block, part_set: PartSet, seen_commit: Commit
    ) -> None:
        self.save_block_batch([(block, part_set, seen_commit)])

    def save_block_batch(self, entries) -> None:
        """Persist a contiguous ascending run of blocks in ONE atomic
        db.write_batch (entries: [(block, part_set, seen_commit)]).

        The blocksync window pipeline stages a whole verified window
        and flushes it here — one sqlite transaction / one memdb lock
        round per window instead of per block (docs/PERF.md host
        plane). The batch is all-or-nothing, so the store can never be
        observed mid-window; crash-wise a flushed window leaves the
        store AHEAD of the state, which is the handshake-supported
        direction (consensus/replay.py replays store blocks the app
        has not seen)."""
        if not entries:
            return
        with self._lock:
            expect = self._height
            sets: List = []
            for block, part_set, seen_commit in entries:
                h = block.height
                if expect > 0 and h != expect + 1:
                    raise ValueError(
                        f"non-contiguous block save: have {expect}, "
                        f"got {h}"
                    )
                sets.extend(
                    self._block_sets(block, part_set, seen_commit)
                )
                expect = h
            if self._base == 0:
                self._base = entries[0][0].height
                sets.append(
                    (b"base", self._base.to_bytes(8, "big"))
                )
            sets.append((b"height", expect.to_bytes(8, "big")))
            self.db.write_batch(sets)
            self._height = expect

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        # canonical re-encode, same reasoning as save_block's SC record
        # (statesync/bootstrap commits come from light blocks whose
        # wire encoding is never canonicality-checked, only their
        # signatures verify)
        self.db.set(_hkey(b"SC:", height), codec.encode_commit(commit))

    def save_extended_commit(self, height: int, ec_bytes: bytes) -> None:
        self.db.set(_hkey(b"EC:", height), ec_bytes)

    def delete_latest_block(self) -> None:
        """Remove the tip block (reference store.go DeleteLatestBlock,
        used by rollback --hard)."""
        h = self._height
        if h == 0:
            return
        meta = self.load_block_meta(h)
        deletes = [
            _hkey(b"H:", h),
            _hkey(b"C:", h - 1),
            _hkey(b"SC:", h),
            _hkey(b"EC:", h),
        ]
        if meta is not None:
            deletes.append(b"BH:" + meta.block_id.hash)
            for i in range(meta.block_id.part_set_header.total):
                deletes.append(_hkey(b"P:", h) + i.to_bytes(4, "big"))
        with self._lock:
            self._height = h - 1
            self.db.write_batch(
                [(b"height", (h - 1).to_bytes(8, "big"))], deletes
            )

    # --- load ---------------------------------------------------------

    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        b = self.db.get(_hkey(b"H:", height))
        return BlockMeta.decode(b) if b else None

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            pb = self.db.get(_hkey(b"P:", height) + i.to_bytes(4, "big"))
            if pb is None:
                return None
            parts.append(_decode_part(pb))
        data = b"".join(p.bytes_ for p in parts)
        return codec.decode_block(data)

    def load_block_by_hash(self, h: bytes) -> Optional[Block]:
        hb = self.db.get(b"BH:" + h)
        if hb is None:
            return None
        return self.load_block(int.from_bytes(hb, "big"))

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        pb = self.db.get(_hkey(b"P:", height) + index.to_bytes(4, "big"))
        return _decode_part(pb) if pb else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        b = self.db.get(_hkey(b"C:", height))
        return codec.decode_commit(b) if b else None

    def load_seen_commit(self, height: int) -> Optional[Commit]:
        b = self.db.get(_hkey(b"SC:", height))
        return codec.decode_commit(b) if b else None

    def load_extended_commit(self, height: int) -> Optional[bytes]:
        return self.db.get(_hkey(b"EC:", height))

    # --- prune --------------------------------------------------------

    def prune_blocks(self, retain_height: int) -> int:
        """Delete blocks below retain_height; returns number pruned
        (reference store/store.go PruneBlocks)."""
        if retain_height <= self._base:
            return 0
        pruned = 0
        deletes = []
        for h in range(self._base, min(retain_height, self._height)):
            meta = self.load_block_meta(h)
            if meta is None:
                continue
            deletes.append(_hkey(b"H:", h))
            deletes.append(_hkey(b"C:", h))
            deletes.append(_hkey(b"SC:", h))
            deletes.append(_hkey(b"EC:", h))
            deletes.append(b"BH:" + meta.block_id.hash)
            for i in range(meta.block_id.part_set_header.total):
                deletes.append(_hkey(b"P:", h) + i.to_bytes(4, "big"))
            pruned += 1
        with self._lock:
            self.db.write_batch(
                [(b"base", retain_height.to_bytes(8, "big"))], deletes
            )
            self._base = retain_height
        return pruned


def _encode_part(part: Part) -> bytes:
    pf = (
        proto.field_varint(1, part.proof.total)
        + proto.field_varint(2, part.proof.index)
        + proto.field_bytes(3, part.proof.leaf_hash)
        + b"".join(proto.field_bytes(4, a) for a in part.proof.aunts)
    )
    return (
        proto.field_varint(1, part.index)
        + proto.field_bytes(2, part.bytes_)
        + proto.field_message(3, pf)
    )


def _decode_part(b: bytes) -> Part:
    from ..crypto.merkle import Proof

    m = proto.parse(b)
    pm = proto.parse(proto.get1(m, 3, b""))
    return Part(
        index=proto.get1(m, 1, 0),
        bytes_=proto.get1(m, 2, b""),
        proof=Proof(
            total=proto.get1(pm, 1, 0),
            index=proto.get1(pm, 2, 0),
            leaf_hash=proto.get1(pm, 3, b""),
            aunts=pm.get(4, []),
        ),
    )
