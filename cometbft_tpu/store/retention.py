"""Storage lifecycle plane: retention-windowed pruning + node-side
snapshot persistence (ISSUE 17; ROADMAP item 5(b) "pruning/retention
driver").

Until now every pruning primitive existed but nothing drove them: the
node was immortal-storage-only. The ``RetentionPlane`` is a
node-owned background service that reconciles the node-side retention
window (``[storage] retain_blocks / retain_states / retain_index``)
with the app's ``retain_height`` from ABCI Commit — **min wins**: the
node only ever keeps MORE than the app allows pruning, never less —
and prunes blocks, states, index rows, sealed WAL files and committed
evidence markers in bounded batches OFF the consensus loop.

Crash-safety direction (one rule, every leg): the delete batch and
the base-marker advance it covers land in ONE atomic ``write_batch``
— ``BlockStore.prune_blocks`` ships this for blocks (``base`` key),
``state.indexer.prune_index`` for index rows (``idx:base``). A crash
between batches resumes idempotently: the next reconcile re-computes
the same target and continues from the committed base. Batches are
sliced ``prune_batch`` heights at a time so no single batch holds a
store lock for an unbounded scan (the shape bftlint ASY120 enforces).

Two floors cap every prune target:
  - the newest locally-held snapshot (``statesync/snapshots.py``):
    with snapshotting on, a pruned node must still hold one complete
    snapshot to bootstrap a fresh joiner — no snapshot yet means NO
    pruning yet;
  - in-flight statesync serves (``serving()``): a chunk being
    streamed to a joiner must not be pruned out from under it.

Snapshot generation rides the existing ABCI snapshot seam: at
``snapshot_interval`` cadence the plane mirrors the app's newest
advertised snapshot (``list_snapshots`` + ``load_snapshot_chunk``)
into the on-disk ``SnapshotStore`` — so ``_serve_snapshots`` serves
across restarts even for apps that keep RAM-only snapshots. An app
wired directly to the same store (models/kvstore.py) makes the
mirror a no-op.

Observability: ``storage.prune`` / ``storage.snapshot`` spans
(budgets in tools/span_budgets.toml), a ``store.retention`` registry
entry, and bridge metrics ``cometbft_storage_base_height`` /
``cometbft_storage_pruned_total`` / ``cometbft_storage_disk_bytes``
(utils/metrics.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import threading
from collections import Counter
from contextlib import contextmanager
from typing import Optional

from ..trace import NOOP as TRACE_NOOP
from ..utils.fail import fail_point
from ..utils.log import get_logger

_log = get_logger("retention")


def _du(path: str) -> int:
    total = 0
    for dirpath, _, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return total


class RetentionPlane:
    """Background retention reconciler + snapshot persister."""

    def __init__(
        self,
        storage_config,
        block_store,
        state_store,
        tx_indexer=None,
        block_indexer=None,
        evpool=None,
        snapshot_store=None,
        proxy=None,
        wal_path: Optional[str] = None,
        home: Optional[str] = None,
        tracer=TRACE_NOOP,
    ):
        self.cfg = storage_config
        self.block_store = block_store
        self.state_store = state_store
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.evpool = evpool
        self.snapshot_store = snapshot_store
        self.proxy = proxy
        self.wal_path = wal_path
        self.home = home
        self.tracer = tracer
        # the app's retain_height from the last ABCI Commit (0 = the
        # app allows no pruning); written from the consensus thread
        # via the BlockExecutor hook, read here — a bare int store is
        # atomic under the GIL
        self._app_retain = 0
        # in-flight statesync serve floor: height -> active serves
        self._serves: Counter = Counter()
        self._serve_lock = threading.Lock()
        # one reconcile at a time (timer tick racing an explicit call)
        self._reconcile_lock = threading.Lock()
        # chaos seam (chaos/net.py crash_mid_prune /
        # snapshot_during_prune): called before every bounded batch,
        # right after the fail_point. An in-process nemesis installs a
        # hook that raises (abort mid-pass, the crash window) or
        # parks (hold the pass mid-batch) — the stand-in for
        # FAIL_TEST_INDEX's os._exit, which would kill the whole
        # test process
        self.batch_hook = None
        self._task = None
        # counters (stats() / metrics bridge)
        self.pruned_blocks_total = 0
        self.pruned_index_total = 0
        self.pruned_states_passes = 0
        self.pruned_wal_files = 0
        self.pruned_evidence_total = 0
        self.snapshots_taken = 0
        self.reconciles = 0
        self.last_prune_s = 0.0
        # OS thread ident of the last reconcile pass — bench.py's
        # lifecycle leg asserts it differs from the event-loop thread
        # (prune work must never run on the consensus path)
        self.last_thread_ident = None

    # --- enablement ---------------------------------------------------

    @property
    def enabled(self) -> bool:
        """Any lifecycle work configured at all. Off (every knob 0)
        keeps exact reference semantics: immortal storage, app
        retain_height handled by the legacy inline path."""
        c = self.cfg
        return bool(
            c.retain_blocks
            or c.retain_states
            or c.retain_index
            or c.snapshot_interval
        )

    # --- inputs -------------------------------------------------------

    def notify_retain_height(self, retain_height: int) -> None:
        """BlockExecutor hook (consensus thread): the app's latest
        ABCI Commit retain_height. Recorded only — pruning happens on
        the plane's own cadence, off the consensus loop."""
        self._app_retain = int(retain_height)

    @contextmanager
    def serving(self, height: int):
        """Pin ``height`` against pruning while a statesync chunk or
        snapshot listing for it is being served to a joiner."""
        with self._serve_lock:
            self._serves[height] += 1
        try:
            yield
        finally:
            with self._serve_lock:
                self._serves[height] -= 1
                if self._serves[height] <= 0:
                    del self._serves[height]

    def _serve_floor(self) -> Optional[int]:
        with self._serve_lock:
            return min(self._serves) if self._serves else None

    # --- target reconciliation (min wins) -----------------------------

    def _target(self, height: int, window: int) -> int:
        """Prune target for one leg: min-reconcile the node window
        against the app's retain_height, then cap under the snapshot
        and in-flight-serve floors. 0 = nothing prunable."""
        cands = []
        if window > 0:
            cands.append(height - window)
        rh = self._app_retain
        if rh > 0:
            cands.append(rh)
        if not cands:
            return 0
        t = min(cands)
        if self.cfg.snapshot_interval > 0 and self.snapshot_store:
            # never prune above (or into) the newest held snapshot;
            # none held yet -> no pruning yet
            t = min(t, self.snapshot_store.latest_height())
        floor = self._serve_floor()
        if floor is not None:
            t = min(t, floor)
        return max(0, min(t, height))

    def _batch_point(self) -> None:
        """One bounded batch is about to commit. The fail_point is the
        subprocess crash seam (FAIL_TEST_INDEX -> os._exit, the power
        cut); ``batch_hook`` is the in-process chaos seam (abort or
        park the pass mid-batch without killing the harness)."""
        fail_point("retention-prune-batch")
        hook = self.batch_hook
        if hook is not None:
            hook()

    # --- the reconcile pass (worker thread / sync drivers) ------------

    def reconcile_once(self) -> dict:
        """One full lifecycle pass: snapshot first (it RAISES the
        prune floor), then prune every leg in bounded batches.
        Synchronous — the async loop runs it via to_thread; tests and
        the compressed-time soak call it directly."""
        with self._reconcile_lock:
            import time as _time

            self.last_thread_ident = threading.get_ident()
            t0 = _time.monotonic()
            out = {
                "snapshot": 0,
                "blocks": 0,
                "index": 0,
                "states": 0,
                "wal_files": 0,
                "evidence": 0,
            }
            try:
                if self.cfg.snapshot_interval > 0:
                    out["snapshot"] = self._maybe_snapshot()
                self._prune_pass(out)
            finally:
                self.reconciles += 1
                self.last_prune_s = _time.monotonic() - t0
            return out

    def _maybe_snapshot(self) -> int:
        """Mirror the app's newest advertised snapshot to disk once
        it is ``snapshot_interval`` past the newest one held."""
        if self.proxy is None or self.snapshot_store is None:
            return 0
        snaps = self.proxy.snapshot.list_snapshots() or []
        if not snaps:
            return 0
        newest = max(snaps, key=lambda s: s.height)
        held = self.snapshot_store.latest_height()
        if newest.height <= held or (
            held and newest.height < held + self.cfg.snapshot_interval
        ):
            return 0
        with self.tracer.span(
            "storage.snapshot",
            tid="retention",
            height=newest.height,
            chunks=newest.chunks,
        ):
            parts = []
            for i in range(newest.chunks):
                parts.append(
                    self.proxy.snapshot.load_snapshot_chunk(
                        newest.height, newest.format, i
                    )
                    or b""
                )
            blob = b"".join(parts)
            if hashlib.sha256(blob).digest() != newest.hash:
                _log.error(
                    "app snapshot chunks do not hash to the "
                    "advertised hash; not persisting",
                    height=newest.height,
                )
                return 0
            self.snapshot_store.save(
                newest.height,
                blob,
                format_=newest.format,
                metadata=newest.metadata,
            )
        self.snapshots_taken += 1
        return 1

    def _prune_pass(self, out: dict) -> None:
        height = self.block_store.height()
        batch = max(1, int(self.cfg.prune_batch))
        # blocks: slice prune_blocks so each call is ONE bounded
        # atomic batch (deletes + base advance together)
        bt = self._target(height, self.cfg.retain_blocks)
        base = self.block_store.base()
        if bt > base:
            with self.tracer.span(
                "storage.prune",
                tid="retention",
                kind="blocks",
                target=bt,
                base=base,
            ):
                while base < bt:
                    step = min(base + batch, bt)
                    self._batch_point()
                    out["blocks"] += self.block_store.prune_blocks(step)
                    base = step
            self.pruned_blocks_total += out["blocks"]
        # index rows: same slicing, idx:base advances with each batch
        it = self._target(height, self.cfg.retain_index)
        if (
            it > 0
            and self.tx_indexer is not None
            and self.block_indexer is not None
            and getattr(self.tx_indexer, "db", None) is not None
            and getattr(self.tx_indexer, "db", None)
            is getattr(self.block_indexer, "db", None)
        ):
            from ..state.indexer import prune_index

            ibase = self.tx_indexer.base_height()
            if it > ibase:
                with self.tracer.span(
                    "storage.prune",
                    tid="retention",
                    kind="index",
                    target=it,
                    base=ibase,
                ):
                    while ibase < it:
                        step = min(ibase + batch, it)
                        self._batch_point()
                        out["index"] += prune_index(
                            self.tx_indexer, self.block_indexer, step
                        )
                        ibase = step
                self.pruned_index_total += out["index"]
        # states: prune_states keeps its own validator-info anchor
        # discipline; one pass per reconcile (row counts there are
        # per-height small)
        st = self._target(height, self.cfg.retain_states)
        if st > 0:
            with self.tracer.span(
                "storage.prune", tid="retention", kind="states", target=st
            ):
                self._batch_point()
                self.state_store.prune_states(st)
                out["states"] = 1
            self.pruned_states_passes += 1
        # WAL: sealed rotated files entirely below the retained end-
        # height (file granularity; the head is never touched)
        if self.wal_path and bt > 0:
            from ..consensus.wal import prune_group_below

            n, _ = prune_group_below(self.wal_path, bt)
            out["wal_files"] = n
            self.pruned_wal_files += n
        # evidence: committed markers aged past the max-age window
        if self.evpool is not None and bt > 0:
            try:
                n = self.evpool.prune_below(bt)
            except Exception:
                n = 0
            out["evidence"] = n
            self.pruned_evidence_total += n

    # --- async lifecycle (Node.start / Node._shutdown) ----------------

    async def start(self) -> None:
        """Spawn the background reconcile loop (no-op when no knob is
        set). Every pass runs in a worker thread: the event loop —
        and through it the consensus task — never carries prune
        work."""
        if not self.enabled or self._task is not None:
            return
        from ..utils.tasks import spawn

        self._task = spawn(self._loop(), name="retention-reconcile")

    async def _loop(self) -> None:
        interval = max(0.05, float(self.cfg.prune_interval_s))
        while True:
            await asyncio.sleep(interval)
            try:
                await asyncio.to_thread(self.reconcile_once)
            except asyncio.CancelledError:
                raise
            except Exception:
                # one failed pass (transient sqlite lock, disk
                # hiccup) must not kill the plane for the rest of
                # the process — the next tick retries the same
                # idempotent targets
                import traceback

                traceback.print_exc()

    async def stop(self) -> None:
        """Bounded stop (ASY110): cancel the loop, reap it, then
        drain any reconcile pass still running in its worker thread —
        cancelling an `await to_thread` abandons the await, not the
        thread, and Node._shutdown closes the stores right after."""
        t, self._task = self._task, None
        if t is not None:
            t.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(t, return_exceptions=True), 5.0
                )
            except asyncio.TimeoutError:
                pass

        def _drain() -> None:
            if self._reconcile_lock.acquire(timeout=5.0):
                self._reconcile_lock.release()

        await asyncio.to_thread(_drain)

    # --- observability ------------------------------------------------

    def disk_bytes(self) -> Optional[int]:
        return _du(self.home) if self.home else None

    def stats(self) -> dict:
        s = {
            "enabled": self.enabled,
            "base_height": self.block_store.base(),
            "index_base_height": (
                self.tx_indexer.base_height()
                if self.tx_indexer is not None
                and hasattr(self.tx_indexer, "base_height")
                else 0
            ),
            "app_retain_height": self._app_retain,
            "pruned_blocks_total": self.pruned_blocks_total,
            "pruned_index_total": self.pruned_index_total,
            "pruned_wal_files": self.pruned_wal_files,
            "pruned_evidence_total": self.pruned_evidence_total,
            "snapshots_taken": self.snapshots_taken,
            "reconciles": self.reconciles,
            "last_prune_s": round(self.last_prune_s, 6),
        }
        if self.snapshot_store is not None:
            s["snapshots"] = self.snapshot_store.stats()
        db = self.disk_bytes()
        if db is not None:
            s["disk_bytes"] = db
        return s
