"""Block pool: pipelined block download from peers (reference blocksync/pool.go).

Requesters fetch a sliding window of heights concurrently; blocks are
handed to the verify loop strictly in order. Peer quality feedback:
timeouts and bad blocks ban the peer (fork feature: banned peers +
adaptive peer sorting, reference blocksync/pool.go:79-84,504-522);
faster peers get picked first (simple EWMA latency score).
"""

from __future__ import annotations

import asyncio
import random
import time
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

REQUEST_TIMEOUT_S = 10.0
MAX_PENDING = 64
BAN_DURATION_S = 60.0


def _now() -> float:
    """Monotonic clock, module-level so tests can fake ban expiry
    without touching the event loop's time.monotonic."""
    return time.monotonic()


class PeerError(Exception):
    def __init__(self, peer_id: str, msg: str):
        super().__init__(msg)
        self.peer_id = peer_id


@dataclass
class PoolPeer:
    peer_id: str
    client: object  # BlockSyncPeerClient: async request_block(h)
    base: int = 0
    height: int = 0
    latency_ewma: float = 1.0
    pending: int = 0

    def serves(self, height: int) -> bool:
        return self.base <= height <= self.height


class BlockPool:
    """Downloads [start_height ..] keeping ``self.max_pending`` in
    flight (defaults to MAX_PENDING; the reactor raises it to cover
    its verify-window lookahead — see start_requesters)."""

    def __init__(self, start_height: int):
        self.start_height = start_height
        self.height = start_height  # next height to hand to verify loop
        self.max_pending = MAX_PENDING  # see start_requesters note
        self.peers: Dict[str, PoolPeer] = {}
        # bans live on the POOL, not the PoolPeer: a banned peer that
        # disconnects and re-dials (peer churn) must still be banned,
        # or a byzantine feeder can launder its ban with a reconnect
        self.banned_until: Dict[str, float] = {}
        self.blocks: Dict[int, Tuple[object, str]] = {}  # h -> (block, peer)
        # backpressure telemetry (obs/queues.py registry): worst
        # buffered-window size since start — the pool's pending window
        # is the blocksync plane's bounded queue
        self.blocks_hwm = 0
        # soft per-height exclusions (e.g. "peer lacks the extended
        # commit for h"): skipped when alternatives exist, ignored
        # otherwise — never a liveness risk, unlike a ban
        self.excluded: Dict[int, set] = {}
        self._tasks: Dict[int, asyncio.Task] = {}
        self._new_block = asyncio.Event()
        self._stopped = False
        self.start_time = _now()

    # --- peers --------------------------------------------------------

    def set_peer_range(self, peer_id: str, client, base: int, height: int):
        p = self.peers.get(peer_id)
        if p is None:
            self.peers[peer_id] = PoolPeer(
                peer_id, client, base=base, height=height
            )
        else:
            p.base, p.height = base, height
        # a taller peer may unlock new heights (peers can appear/grow
        # AFTER the pool started in the networked path)
        self.start_requesters()

    def remove_peer(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)
        for h, (blk, pid) in list(self.blocks.items()):
            if pid == peer_id and h >= self.height:
                del self.blocks[h]
                self._maybe_spawn(h)

    def ban_peer(self, peer_id: str, reason: str = "") -> None:
        self.banned_until[peer_id] = _now() + BAN_DURATION_S

    def _prune_bans(self, now: float) -> None:
        """Expired bans are deleted, not just ignored — long syncs churn
        through many one-shot peer ids and the dict must not grow with
        every peer ever banned."""
        for pid in [p for p, t in self.banned_until.items() if t <= now]:
            del self.banned_until[pid]

    def banned_peers(self) -> List[str]:
        """Currently-banned peer ids (introspection for checkers)."""
        now = _now()
        self._prune_bans(now)
        return list(self.banned_until)

    def max_peer_height(self) -> int:
        return max((p.height for p in self.peers.values()), default=0)

    def exclude_peer_for_height(self, height: int, peer_id: str) -> None:
        """Prefer other peers for this one height (no ban)."""
        self.excluded.setdefault(height, set()).add(peer_id)

    def clear_exclusions(self, height: int) -> None:
        self.excluded.pop(height, None)

    def _pick_peer(self, height: int) -> Optional[PoolPeer]:
        now = _now()
        self._prune_bans(now)
        in_range = [p for p in self.peers.values() if p.serves(height)]
        candidates = [
            p
            for p in in_range
            if p.peer_id not in self.banned_until
        ]
        excl = self.excluded.get(height)
        if not candidates:
            # starvation guard: when EVERY peer serving this height is
            # banned, fetching from the least-loaded, least-recently-
            # banned one beats stalling the sync until a ban expires
            # (the liveness counterpart of the soft exclusions above);
            # the requester's failure-path sleep paces the retries.
            # Soft exclusions still steer here — a peer structurally
            # unable to serve this height (e.g. no extended commit)
            # yields to a banned-but-capable alternative
            if not in_range:
                return None
            pool = in_range
            if excl:
                pool = [p for p in in_range if p.peer_id not in excl] or in_range
            return min(
                pool,
                key=lambda p: (
                    p.pending,
                    self.banned_until.get(p.peer_id, 0.0),
                ),
            )
        if excl:
            preferred = [p for p in candidates if p.peer_id not in excl]
            if preferred:
                candidates = preferred
        # adaptive sorting: prefer low latency, few pending requests
        candidates.sort(
            key=lambda p: (p.pending, p.latency_ewma, random.random())
        )
        return candidates[0]

    # --- requesters ---------------------------------------------------
    #
    # max_pending is an instance attribute so the reactor can raise it
    # to cover its verify-window LOOKAHEAD: the pipelined dispatch
    # needs ~2x verify_window buffered blocks or the next-window
    # pre-dispatch never has a tail to work with (found empirically:
    # a 128-wide bench replay had predispatched=0 with the fixed
    # 64-deep pool).

    def start_requesters(self) -> None:
        top = min(
            self.height + self.max_pending - 1, self.max_peer_height()
        )
        for h in range(self.height, top + 1):
            self._maybe_spawn(h)

    def _maybe_spawn(self, height: int) -> None:
        if (
            self._stopped
            or height in self.blocks
            or height in self._tasks
            or height < self.height
            or height > self.max_peer_height()
            or height >= self.height + self.max_pending
        ):
            return
        self._tasks[height] = asyncio.create_task(self._fetch(height))

    async def _fetch(self, height: int) -> None:
        try:
            while not self._stopped:
                peer = self._pick_peer(height)
                if peer is None:
                    await asyncio.sleep(0.05)
                    continue
                peer.pending += 1
                t0 = _now()
                try:
                    block = await asyncio.wait_for(
                        peer.client.request_block(height), REQUEST_TIMEOUT_S
                    )
                    dt = _now() - t0
                    peer.latency_ewma = 0.8 * peer.latency_ewma + 0.2 * dt
                    if block is None:
                        raise PeerError(peer.peer_id, f"no block {height}")
                    self.blocks[height] = (block, peer.peer_id)
                    if len(self.blocks) > self.blocks_hwm:
                        self.blocks_hwm = len(self.blocks)
                    self._new_block.set()
                    return
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # any client failure (timeout, missing block, broken
                    # transport) bans the peer and retries elsewhere;
                    # the requester itself must never die silently. The
                    # sleep paces retries when the starvation guard
                    # keeps handing back a banned, fast-failing peer
                    traceback.print_exc()
                    self.ban_peer(peer.peer_id)
                    await asyncio.sleep(0.05)
                finally:
                    peer.pending -= 1
        finally:
            if self._tasks.get(height) is asyncio.current_task():
                self._tasks.pop(height, None)

    # --- ordered consumption ------------------------------------------

    def peek_window(self, n: int) -> List[Tuple[int, object, str]]:
        """Contiguous run of up to n+1 buffered blocks from pool.height
        (for coalesced commit verification across heights)."""
        out = []
        h = self.height
        while len(out) <= n and h in self.blocks:
            blk, pid = self.blocks[h]
            out.append((h, blk, pid))
            h += 1
        return out

    def pop_request(self) -> None:
        self.blocks.pop(self.height, None)
        self.height += 1
        self.start_requesters()

    def redo_request(self, height: int, ban_peer: Optional[str]) -> None:
        """Invalid block: drop it + all buffered blocks from its peer,
        ban the peer, refetch (reference pool.go
        RemovePeerAndRedoAllPeerRequests)."""
        if ban_peer:
            self.ban_peer(ban_peer, "bad block")
        self.blocks.pop(height, None)
        for h, (blk, pid) in list(self.blocks.items()):
            if pid == ban_peer and h >= self.height:
                del self.blocks[h]
        self.start_requesters()

    def queue_stats(self) -> dict:
        """Pending-window backpressure (obs/queues.py registry). A
        FULL window is normal flow control while syncing, so the
        bound is reported as a soft target, not "maxsize" (which
        would trip the health route's full-queue degraded check)."""
        return {
            "depth": len(self.blocks),
            "high_watermark": self.blocks_hwm,
            "dropped": 0,
            "window_target": self.max_pending,
        }

    def is_caught_up(self) -> bool:
        """Reference blocksync/pool.go:227 IsCaughtUp: at least one
        peer (peers only exist once their status arrived, so heights
        are known), and our chain reaches maxPeerHeight-1 (block H
        needs H+1's commit to verify)."""
        if not self.peers:
            return False
        mx = self.max_peer_height()
        return mx == 0 or self.height >= mx - 1

    async def wait_for_block(self, timeout: float = 0.2) -> None:
        try:
            await asyncio.wait_for(self._new_block.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        self._new_block.clear()

    def stop(self) -> None:
        self._stopped = True
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()
