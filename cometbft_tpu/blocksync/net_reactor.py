"""Blocksync network reactor: channel 0x40 wire protocol around the
BlockPool/BlockSyncReactor verify loop (reference blocksync/reactor.go,
channel id :21).

Messages: StatusRequest/StatusResponse(base, height),
BlockRequest(height), BlockResponse(block, commit), NoBlockResponse.
Peers answering requests serve blocks straight from their store; the
local pool side is bridged through NetPeerClient, which satisfies the
pool's async request_block(height) interface by pairing requests with
responses arriving on the channel."""

from __future__ import annotations

import asyncio
import struct
import traceback
from typing import Callable, Dict, Optional

from ..p2p.node_info import ChannelDescriptor
from ..p2p.reactor import Reactor
from ..utils import codec, proto
from ..utils.tasks import spawn
from .reactor import BlockSyncReactor

BLOCKSYNC_CHANNEL = 0x40

MSG_STATUS_REQUEST = 0x01
MSG_STATUS_RESPONSE = 0x02
MSG_BLOCK_REQUEST = 0x03
MSG_BLOCK_RESPONSE = 0x04
MSG_NO_BLOCK_RESPONSE = 0x05

STATUS_POLL_INTERVAL_S = 2.0


class NetPeerClient:
    """Adapts one remote peer to the pool's request_block interface."""

    def __init__(self, peer, switch=None):
        self.peer = peer
        self.switch = switch  # trace stamping (stamp_msg); may be None
        self.pending: Dict[int, asyncio.Future] = {}

    async def request_block(self, height: int):
        fut = asyncio.get_running_loop().create_future()
        self.pending[height] = fut
        try:
            msg = bytes([MSG_BLOCK_REQUEST]) + struct.pack(">q", height)
            if self.switch is not None:
                msg = self.switch.stamp_msg(
                    BLOCKSYNC_CHANNEL, msg, "bs.request", height=height,
                    peer=self.peer.peer_id,
                )
            await self.peer.send(BLOCKSYNC_CHANNEL, msg)
            return await fut
        finally:
            self.pending.pop(height, None)

    def deliver(self, height: int, block) -> None:
        fut = self.pending.get(height)
        if fut and not fut.done():
            fut.set_result(block)


class BlockSyncNetReactor(Reactor):
    name = "blocksync"

    def __init__(
        self,
        state,
        block_exec,
        block_store,
        on_caught_up: Optional[Callable] = None,
        block_ingestor=None,  # fork: adaptive sync
        active: bool = True,
        local_blocks_chain=None,
    ):
        super().__init__()
        self.block_store = block_store
        self.inner = BlockSyncReactor(
            state,
            block_exec,
            block_store,
            on_caught_up=self._caught_up,
            block_ingestor=block_ingestor,
            local_blocks_chain=local_blocks_chain,
        )
        self.on_caught_up = on_caught_up
        # active=False: full node already caught up, only SERVES blocks
        # (reference: blocksync reactor with blockSync=false)
        self.active = active
        self.clients: Dict[str, NetPeerClient] = {}
        self._status_task: Optional[asyncio.Task] = None
        self._started_pool = False

    def get_channels(self):
        return [
            ChannelDescriptor(BLOCKSYNC_CHANNEL, priority=5, max_msg_size=1 << 22)
        ]

    def _caught_up(self, state) -> None:
        self.active = False
        if self.on_caught_up:
            self.on_caught_up(state)

    # --- lifecycle ----------------------------------------------------

    async def start(self) -> None:
        if self.active:
            await self.inner.start()
            self._started_pool = True
        self._status_task = asyncio.create_task(self._status_routine())

    async def activate(self, state) -> None:
        """Begin syncing from a statesync-bootstrapped state
        (reference statesync -> blocksync phase hand-off)."""
        self.inner.state = state
        self.inner.pool.start_height = state.last_block_height + 1
        self.inner.pool.height = state.last_block_height + 1
        self.active = True
        await self.inner.start()
        self._started_pool = True
        # re-announce + re-query statuses so the pool learns ranges
        if self.switch is not None:
            self.switch.broadcast(
                BLOCKSYNC_CHANNEL, bytes([MSG_STATUS_REQUEST])
            )

    async def stop(self) -> None:
        if self._status_task:
            self._status_task.cancel()
        if self._started_pool:
            # bounded (ASY110): the pool routine can be parked in an
            # executor verify wait — don't let it wedge teardown
            try:
                await asyncio.wait_for(self.inner.stop(), 10.0)
            except asyncio.TimeoutError:
                pass

    async def _status_routine(self) -> None:
        try:
            while True:
                if self.active and self.switch is not None:
                    self.switch.broadcast(
                        BLOCKSYNC_CHANNEL,
                        bytes([MSG_STATUS_REQUEST]),
                        tkind="bs.status",
                    )
                await asyncio.sleep(STATUS_POLL_INTERVAL_S)
        except asyncio.CancelledError:
            raise

    # --- peers --------------------------------------------------------

    def add_peer(self, peer) -> None:
        self.clients[peer.peer_id] = NetPeerClient(peer, self.switch)
        # announce our status so the peer can request from us
        peer.try_send(BLOCKSYNC_CHANNEL, self._status_response())
        if self.active:
            peer.try_send(BLOCKSYNC_CHANNEL, bytes([MSG_STATUS_REQUEST]))

    def remove_peer(self, peer, reason) -> None:
        self.clients.pop(peer.peer_id, None)
        self.inner.pool.remove_peer(peer.peer_id)

    # --- wire ---------------------------------------------------------

    def _status_response(self) -> bytes:
        return bytes([MSG_STATUS_RESPONSE]) + struct.pack(
            ">qq", self.block_store.base(), self.block_store.height()
        )

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        mtype = msg[0]
        body = msg[1:]
        if mtype == MSG_STATUS_REQUEST:
            peer.try_send(BLOCKSYNC_CHANNEL, self._status_response())
        elif mtype == MSG_STATUS_RESPONSE:
            base, height = struct.unpack(">qq", body)
            cli = self.clients.get(peer.peer_id)
            if cli and self.active:
                self.inner.pool.set_peer_range(
                    peer.peer_id, cli, max(base, 1), height
                )
        elif mtype == MSG_BLOCK_REQUEST:
            (height,) = struct.unpack(">q", body)
            block = self.block_store.load_block(height)
            if block is None:
                peer.try_send(
                    BLOCKSYNC_CHANNEL,
                    bytes([MSG_NO_BLOCK_RESPONSE]) + struct.pack(">q", height),
                )
                return
            payload = proto.field_bytes(1, codec.encode_block(block))
            # vote extensions: ship the stored extended commit so the
            # syncing node can later propose with ExtendedCommitInfo
            # (reference blocksync BlockResponse.ExtCommit,
            # reactor.go:648)
            ec = self.block_store.load_extended_commit(height)
            if ec:
                payload += proto.field_bytes(2, ec)
            resp = bytes([MSG_BLOCK_RESPONSE]) + payload
            if self.switch is not None:
                resp = self.switch.stamp_msg(
                    BLOCKSYNC_CHANNEL, resp, "bs.block",
                    height=height, peer=peer.peer_id,
                )
            spawn(
                peer.send(BLOCKSYNC_CHANNEL, resp),
                name="blocksync-block-response",
            )
        elif mtype == MSG_BLOCK_RESPONSE:
            m = proto.parse(body)
            block = codec.decode_block(proto.get1(m, 1, b""))
            ec_bytes = proto.get1(m, 2, b"")
            if ec_bytes:
                # carried out-of-band to the verify/apply loop (the
                # pool's data path is block-shaped)
                block._ec_bytes = ec_bytes
            cli = self.clients.get(peer.peer_id)
            if cli:
                cli.deliver(block.height, block)
        elif mtype == MSG_NO_BLOCK_RESPONSE:
            (height,) = struct.unpack(">q", body)
            cli = self.clients.get(peer.peer_id)
            if cli:
                cli.deliver(height, None)
        else:
            raise ValueError(f"unknown blocksync msg type {mtype}")
