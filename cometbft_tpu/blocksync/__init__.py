from .pool import BlockPool, PeerError  # noqa: F401
from .reactor import BlockSyncReactor  # noqa: F401
