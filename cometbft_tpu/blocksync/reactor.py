"""Blocksync reactor: the catch-up verify/apply loop.

Parity with reference blocksync/reactor.go poolRoutine (:560-700), with
the TPU-native twist: instead of verifying one commit at a time
(VerifyCommit at :631), the loop coalesces a WINDOW of buffered heights
and verifies all their commits in one signature-lane dispatch
(types.verify_commits_coalesced) — the north-star 10k-block replay
amortizes ~window x validators signatures per XLA call. Invalid windows
fall back to per-height verification to pinpoint the bad peer.

Block h is verified using block (h+1).LastCommit, i.e. a window of K
applies needs K+1 buffered blocks, exactly like PeekTwoBlocks in the
reference but K-wide.
"""

from __future__ import annotations

import asyncio
import time
import traceback
from typing import Callable, Optional

from .. import types as T
from ..trace import NOOP as TRACE_NOOP
from ..types.validation import (
    verify_commits_coalesced_async,
)
from ..utils import codec
from ..utils.log import get_logger
from .pool import BlockPool

_log = get_logger("blocksync")

VERIFY_WINDOW = 32
SWITCH_TO_CONSENSUS_INTERVAL_S = 1.0
# Apply a block without its extended commit after this many fetches of
# the height came back EC-less (liveness: no reachable peer may hold
# the EC — see _check_extended_commit).
EC_MISS_TOLERANCE = 2


class MissingExtendedCommit(ValueError):
    """Peer served a block without an EC at an extension-enabled
    height: possibly an honest gap, never a verification failure."""


class _PrefixErrors:
    """First ``n`` per-job errors of a wider coalesced handle (the
    lookahead covered more heights than this pass applies)."""

    __slots__ = ("_h", "_n")

    def __init__(self, handle, n: int) -> None:
        self._h = handle
        self._n = n

    def result(self):
        return self._h.result()[: self._n]


class _SplicedErrors:
    """Lookahead verdicts for the first ``n`` jobs + a fresh dispatch
    for the remainder, in job order (the pool refilled after the
    lookahead was sized)."""

    __slots__ = ("_a", "_b", "_n")

    def __init__(self, pre, rest, n: int) -> None:
        self._a = pre
        self._b = rest
        self._n = n

    def result(self):
        return self._a.result()[: self._n] + self._b.result()


class BlockSyncReactor:
    def __init__(
        self,
        state,
        block_exec,
        block_store,
        pool: Optional[BlockPool] = None,
        signature_cache: Optional[T.SignatureCache] = None,
        on_caught_up: Optional[Callable] = None,
        block_ingestor=None,  # fork: adaptive sync ingest hook
        verify_window: int = VERIFY_WINDOW,
        local_blocks_chain=None,  # fn(state)->bool, reactor.go:448
    ):
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.pool = pool or BlockPool(state.last_block_height + 1)
        # the pipelined verify needs ~2x the verify window buffered
        # (current window + pre-dispatched lookahead + the +1 commit
        # block); a pool shallower than that silently disables the
        # overlap (see pool.start_requesters)
        self.pool.max_pending = max(
            self.pool.max_pending, 2 * verify_window + 2
        )
        self.sig_cache = signature_cache or T.SignatureCache()
        self.on_caught_up = on_caught_up
        self.ingestor = block_ingestor
        self.window = verify_window
        self.local_blocks_chain = local_blocks_chain
        self.blocks_applied = 0
        # height -> set of peer ids that served the height EC-less
        self._ec_misses: dict = {}
        # pipelined verify: (key, handle) for the NEXT window's
        # already-dispatched signature batch (see _process_window)
        self._inflight = None
        self.pipeline_stats = {
            "reused": 0,        # pre-dispatched handles consumed
            "dispatched": 0,    # fresh (non-pipelined) dispatches
            "predispatched": 0, # lookahead dispatches issued
            "discarded": 0,     # handles dropped (redo/valset/reshuffle)
        }
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        # tracing plane (trace/): node wiring swaps in the per-node
        # tracer; last_window_bps feeds the Prometheus window-
        # throughput gauge (utils/metrics.py)
        self.tracer = TRACE_NOOP
        self.last_window_bps = 0.0

    # --- lifecycle ----------------------------------------------------

    async def start(self) -> None:
        self.pool.start_requesters()
        self._task = asyncio.create_task(self._pool_routine())

    async def stop(self) -> None:
        self._stopped = True
        self.pool.stop()
        if self._task:
            self._task.cancel()
            try:
                # bounded (ASY110): the pool routine may be awaiting
                # an executor-parked verify — abandon it past budget
                await asyncio.wait_for(self._task, 10.0)
            except asyncio.TimeoutError:
                pass
            except asyncio.CancelledError:
                if not self._task.cancelled():
                    raise  # outer cancel of stop() itself: propagate
            except Exception:
                traceback.print_exc()

    # --- the verify/apply loop ----------------------------------------

    async def _pool_routine(self) -> None:
        last_switch_check = time.monotonic()
        while not self._stopped:
            if time.monotonic() - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL_S:
                last_switch_check = time.monotonic()
                # switch when caught up, OR when blocksync cannot
                # proceed without our own votes (we hold >=1/3 power,
                # reference reactor.go:543 + localNodeBlocksTheChain)
                if self.pool.is_caught_up() or (
                    self.local_blocks_chain is not None
                    and self.local_blocks_chain(self.state)
                ):
                    _log.info(
                        "caught up, leaving blocksync",
                        height=self.state.last_block_height,
                        applied=self.blocks_applied,
                    )
                    if self.on_caught_up:
                        self.on_caught_up(self.state)
                    return
            # peek one extra window of lookahead: _process_window
            # pre-dispatches the NEXT window's signature batch before
            # applying the current one (device work overlaps host
            # decode/apply — docs/PERF.md "overlapped replay dispatch")
            window = self.pool.peek_window(self.window * 2)
            if len(window) < 2:
                await self.pool.wait_for_block()
                continue
            try:
                if self.ingestor is None:
                    # overlapped path: the blocking verify wait runs
                    # in an executor, so the loop stays responsive
                    # (and window K's host apply overlaps window
                    # K+1's pool verification — docs/PERF.md host
                    # plane)
                    applied = await self._process_window_overlapped(
                        window
                    )
                else:
                    # adaptive mode: consensus shares this loop, and
                    # the blocking pass serializes against it — an
                    # await inside the pass would let consensus
                    # commit mid-window against the pass's state view
                    applied = self._process_window(window)
            except asyncio.CancelledError:
                raise
            except Exception:
                traceback.print_exc()
                applied = 0
            if applied == 0:
                await asyncio.sleep(0.01)
            await asyncio.sleep(0)  # yield

    def _process_window(self, window) -> int:
        """Verify all verifiable heights in the window with ONE batch
        dispatch, then apply them in order. Returns #applied.

        Blocking form (tests, adaptive/ingestor mode); the pool
        routine's plain path goes through _process_window_overlapped,
        which parks the verify wait in an executor instead."""
        t0 = time.monotonic()
        with self.tracer.span(
            "blocksync.window.prepare", tid="blocksync"
        ):
            prep = self._prepare_window(window)
        if prep is None:
            return 0
        window, jobs, handle = prep
        with self.tracer.span(
            "blocksync.window.verify_wait", tid="blocksync",
            jobs=len(jobs),
        ):
            errors = handle.result()
        pre = self._predispatch_lookahead(len(jobs))
        with self.tracer.span(
            "blocksync.window.apply", tid="blocksync", jobs=len(jobs)
        ):
            applied = self._apply_window(window, jobs, errors, pre)
        self._observe_window(applied, time.monotonic() - t0)
        return applied

    async def _process_window_overlapped(self, window) -> int:
        """Same pass as _process_window, but the blocking verify wait
        runs in the default executor: the event loop keeps serving
        peer fetches/heartbeats while the parallel host plane (or the
        device) chews on the window's signatures, and the lookahead
        window pre-dispatched by _prepare_window verifies on pool
        threads WHILE this pass's host apply runs — overlap with no
        device required."""
        t0 = time.monotonic()
        with self.tracer.span(
            "blocksync.window.prepare", tid="blocksync"
        ):
            prep = self._prepare_window(window)
        if prep is None:
            return 0
        window, jobs, handle = prep
        # the executor-parked wait is where the verify plane's wall
        # hides (PR 3): its span length vs apply's is the overlap
        sp = self.tracer.span(
            "blocksync.window.verify_wait", tid="blocksync",
            jobs=len(jobs),
        )
        try:
            errors = await asyncio.get_running_loop().run_in_executor(
                None, handle.result
            )
        finally:
            sp.end()
        pre = self._predispatch_lookahead(len(jobs))
        with self.tracer.span(
            "blocksync.window.apply", tid="blocksync", jobs=len(jobs)
        ):
            applied = self._apply_window(window, jobs, errors, pre)
        self._observe_window(applied, time.monotonic() - t0)
        return applied

    def _prepare_window(self, window):
        """Dispatch (or reuse) the window's coalesced signature batch.
        Returns None when nothing is verifiable this pass, else
        (window, jobs, handle). The lookahead is NOT dispatched here:
        the caller issues _predispatch_lookahead after this handle's
        verdicts resolve, when the pool reflects the refill that
        happened during the wait.

        The batch uses the CURRENT state's validator set, so it must
        stop at the first height whose header advertises a different
        validators_hash (valset change mid-window): those heights are
        verified on a later pass once the state has advanced. The hash
        is only used to LIMIT the batch — each block is still fully
        validated against the locally-derived valset when applied."""
        if self.ingestor is not None:
            # adaptive mode: consensus may ALSO be committing heights
            # (its own rounds / commit_block catch-up). Track its state
            # and drop heights it already owns, else the window would
            # verify against a stale valset and ban honest peers.
            self.state = self.ingestor.state
            while window and window[0][0] < self.ingestor.rs.height:
                self.pool.pop_request()
                self.blocks_applied += 1
                window = window[1:]
            if len(window) < 2:
                return None
        # take (and clear) the pre-dispatched handle FIRST: every exit
        # from this pass either consumes it or drops it — a handle
        # must never survive a pass whose window it was not checked
        # against (e.g. the head-mismatch refetch below)
        inflight, self._inflight = self._inflight, None
        # block at window[i] is verified by window[i+1].last_commit
        vals_hash = self.state.validators.hash()
        jobs, key = self._build_jobs(window, vals_hash, self.window - 1)
        if not jobs:
            if inflight is not None:
                self.pipeline_stats["discarded"] += 1
            if len(window) >= 1:
                # head block claims a different valset than our state
                # derives -> it cannot validate; refetch elsewhere
                h, _, peer = window[0]
                self.pool.redo_request(h, peer)
            return None
        # Pipelined verify: reuse the handle pre-dispatched on the
        # previous pass when its inputs CONTENT-match this window —
        # the key is content-based (valset hash + every involved
        # block's hash), so redo/ban refetches, valset changes and
        # pool reshuffles all miss it and a wrong verdict can never
        # be consumed. Length drift (the pool refills between the
        # lookahead peek and this pass) reuses the matching prefix
        # and dispatches only the remainder (_reuse_inflight).
        handle = (
            self._reuse_inflight(inflight, jobs, key)
            if inflight is not None
            else None
        )
        if handle is None:
            if inflight is not None:
                self.pipeline_stats["discarded"] += 1
            handle = verify_commits_coalesced_async(
                self.state.chain_id,
                jobs,
                cache=self.sig_cache,
                priority=T.PRIORITY_CATCHUP,
            )
            self.pipeline_stats["dispatched"] += 1
        return window, jobs, handle

    def _reuse_inflight(self, inflight, jobs, key):
        """Content-match the pre-dispatched handle against this
        pass's jobs, tolerating LENGTH drift in either direction
        (each coalesced job is independent, so verdict prefixes
        compose):

        - lookahead ⊇ window: consume the prefix of its verdicts;
        - lookahead ⊂ window (the pool refilled after the lookahead
          peek): consume ALL its verdicts and dispatch a fresh batch
          for just the remainder, spliced in order.

        Any content mismatch — a refetched block, a valset change —
        returns None and the caller drops the handle. Returns a
        result()-bearing handle or None."""
        pre_key, pre_handle = inflight
        if pre_key[0] != key[0]:
            return None
        pre_hs, hs = pre_key[1], key[1]
        if len(hs) <= len(pre_hs):
            if pre_hs[: len(hs)] != hs:
                return None
            self.pipeline_stats["reused"] += 1
            if len(hs) == len(pre_hs):
                return pre_handle
            return _PrefixErrors(pre_handle, len(hs) - 1)
        if hs[: len(pre_hs)] != pre_hs:
            return None
        n_pre = len(pre_hs) - 1
        rest_handle = verify_commits_coalesced_async(
            self.state.chain_id,
            jobs[n_pre:],
            cache=self.sig_cache,
            priority=T.PRIORITY_CATCHUP,
        )
        self.pipeline_stats["reused"] += 1
        self.pipeline_stats["dispatched"] += 1
        return _SplicedErrors(pre_handle, rest_handle, n_pre)

    def _predispatch_lookahead(self, n_skip: int):
        """Dispatch the NEXT window's batch before applying this one:
        the verification plane (device, or the host pool) chews on
        window K+1 while the host decodes/applies window K
        (docs/PERF.md "overlapped replay dispatch"). Peeked FRESH
        here — after this window's verdicts resolved — so the
        lookahead covers the blocks the requesters pulled in WHILE
        the verify was pending; peeking at prepare time instead sizes
        the lookahead to the pre-refill pool and the next pass's
        (longer) window misses the content key on every pass. Built
        against the pre-apply valset — sound because only heights
        whose headers claim the SAME validators_hash enter a batch,
        and the reuse key check re-validates against the post-apply
        state before any verdict is consumed."""
        tail = self.pool.peek_window(self.window * 2)[n_skip:]
        if len(tail) < 2:
            return None
        pre_jobs, pre_key = self._build_jobs(
            tail, self.state.validators.hash(), self.window - 1
        )
        if not pre_jobs:
            return None
        self.pipeline_stats["predispatched"] += 1
        return (
            pre_key,
            verify_commits_coalesced_async(
                self.state.chain_id,
                pre_jobs,
                cache=self.sig_cache,
                priority=T.PRIORITY_CATCHUP,
            ),
        )

    def _canonical_parts(self, blk, nxt):
        """Part set for ``blk`` — from the peer's wire bytes when they
        produce the part-set header the validators actually signed
        (saves a full re-encode), else from our canonical encoding.

        A peer could serve a NON-canonical encoding of the same block
        (permissive parse) to poison the store; on mismatch every
        memoized wire-bytes shortcut downstream (store save_block
        persists commit._raw_bytes for SC:/C: records) must re-encode
        canonically too, so the memos are dropped."""
        signed_psh = nxt.last_commit.block_id.part_set_header
        raw = getattr(blk, "_raw_bytes", None)
        if raw is not None:
            parts = T.PartSet.from_data(raw)
            if parts.header.hash == signed_psh.hash:
                return parts
            for o in (blk, blk.last_commit):
                if hasattr(o, "_raw_bytes"):
                    del o._raw_bytes
        return T.PartSet.from_data(codec.encode_block(blk))

    def _apply_window(self, window, jobs, errors, pre) -> int:
        """Apply the window's verified blocks in order; returns
        #applied. ``errors`` are the per-job verdicts from the
        coalesced batch (resolved by the caller, possibly in an
        executor)."""
        # Stage the window's store writes and flush them in ONE
        # db.write_batch BEFORE any apply: the commit batch already
        # vouched for every staged block (errors[i] is None ⇒ +2/3 of
        # the valset signed this exact content), and store-ahead-of-
        # state is the crash direction the handshake replays back
        # (consensus/replay.py) — whereas deferring writes past the
        # applies would leave the state ahead of the store, which no
        # recovery path handles. A block that later fails
        # validate_block (a fork — the reference panics there) stays
        # persisted; the refetch loop skips re-saving via the height
        # guard below, and content is hash-pinned by the commit either
        # way. The ingestor path owns its own persistence.
        parts_by_idx = {}
        ec_by_idx = {}
        if self.ingestor is None:
            entries = []
            for i in range(len(jobs)):
                if errors[i] is not None:
                    break
                h, blk, peer_i = window[i]
                _, nxt, _ = window[i + 1]
                parts = self._canonical_parts(blk, nxt)
                parts_by_idx[i] = parts
                # the EC requirement gates persistence: a block whose
                # extended commit is missing/invalid must never enter
                # the store bare (a node serving a bare tip block
                # stalls future joiners — the exact property the
                # at-tip refusal below protects)
                enabled = (
                    self.state.consensus_params.vote_extensions_enabled(
                        h
                    )
                )
                try:
                    ec_bytes = self._check_extended_commit(
                        h, blk, peer_i
                    )
                except Exception:
                    # missing/invalid EC: the apply loop below re-runs
                    # the check at this height and owns the tolerance/
                    # redo logic; nothing at or past it is staged
                    break
                ec_by_idx[i] = (enabled, ec_bytes)
                if self.block_store.height() < h:
                    entries.append((blk, parts, nxt.last_commit))
            if entries:
                with self.tracer.span(
                    "blocksync.window.persist", tid="blocksync",
                    blocks=len(entries),
                ):
                    self.block_store.save_block_batch(entries)
        applied = 0
        for i, _job in enumerate(jobs):
            h, blk, peer = window[i]
            _, nxt, _ = window[i + 1]
            if errors[i] is not None:
                # bad commit: could be a corrupt block h (its hash feeds
                # the expected BlockID) OR a corrupt h+1.LastCommit ->
                # ban BOTH senders and refetch, like the reference's
                # handleValidationFailure (blocksync/reactor.go:749).
                _log.error(
                    "commit verification failed, refetching",
                    height=h,
                    peer=str(peer)[:12],
                    err=repr(errors[i]),
                )
                self.pool.redo_request(h, peer)
                if window[i + 1][2] != peer:
                    self.pool.redo_request(h + 1, window[i + 1][2])
                break
            bid = jobs[i][1]
            try:
                self.block_exec.validate_block(
                    self.state, blk, skip_commit_check=True
                )
            except Exception:
                self.pool.redo_request(h, peer)
                break
            try:
                cached = ec_by_idx.get(i)
                if cached is not None and cached[0] == (
                    self.state.consensus_params.vote_extensions_enabled(
                        h
                    )
                ):
                    # verified during window staging, and the
                    # enablement the check assumed still holds under
                    # the evolved state
                    ec_bytes = cached[1]
                else:
                    if cached is not None:
                        # consensus params moved mid-window: the
                        # staged flush persisted this height (and the
                        # rest of the window) under an enablement
                        # that no longer holds — roll the UNAPPLIED
                        # store tip back to h-1 before re-deciding,
                        # so a block whose EC requirement just
                        # flipped on can never outlive this pass bare
                        # (the heights removed are exactly the
                        # staged-not-yet-applied ones; re-applies
                        # fall back to per-block save below)
                        while self.block_store.height() >= h:
                            self.block_store.delete_latest_block()
                    # not staged (an EC decision was pending at this
                    # height) or params moved: run the full check
                    # against the CURRENT state
                    ec_bytes = self._check_extended_commit(
                        h, blk, peer
                    )
            except MissingExtendedCommit as e:
                served = self._ec_misses.setdefault(h, set())
                served.add(peer)
                # Bare-apply rules (the reference hard-rejects EC-less
                # blocks everywhere, blocksync/reactor.go:618-648; we
                # tolerate narrowly for liveness):
                #  - NEVER at the pool's max height — that block is the
                #    switch-to-consensus tip, and a node that applied
                #    it bare cannot propose at tip+1 (no EC to carry)
                #    nor serve the EC to later joiners;
                #  - only after EC_MISS_TOLERANCE *distinct* peers came
                #    back bare (a single byzantine peer that wins every
                #    refetch must not be able to force a bare apply),
                #    or every known peer has (single-peer nets can
                #    never reach the distinct-peer bar).
                # the highest height blocksync can apply is
                # max_peer_height - 1 (block h needs h+1's commit), and
                # is_caught_up switches to consensus there — so THAT is
                # the tip to protect
                at_tip = h >= self.pool.max_peer_height() - 1
                # exhaustion counts only peers whose advertised range
                # can actually serve h — lagging or pruned peers in the
                # denominator would make exhaustion unreachable and
                # stall the sync below tip forever
                can_serve = {
                    pid
                    for pid, p in self.pool.peers.items()
                    if p.base <= h <= p.height
                }
                exhausted = bool(can_serve) and served >= can_serve
                if at_tip or (
                    len(served) < EC_MISS_TOLERANCE and not exhausted
                ):
                    # honest peers can lack the EC: refetch WITHOUT
                    # banning, steering the retry to a DIFFERENT peer
                    # (soft exclusion — the fastest peer would
                    # otherwise be re-picked and win the refetch too)
                    _log.info(
                        "peer lacks extended commit, refetching",
                        height=h,
                        distinct_peers=len(served),
                        at_tip=at_tip,
                    )
                    self.pool.exclude_peer_for_height(h, peer)
                    self.pool.redo_request(h, None)
                    break
                _log.info(
                    "applying historical block without extended commit",
                    height=h,
                    distinct_peers=len(served),
                )
                ec_bytes = None
            except Exception as e:
                _log.error(
                    "extended commit check failed, refetching",
                    height=h,
                    err=repr(e),
                )
                self.pool.redo_request(h, peer)
                break
            # persist the verified EC immediately: every later branch
            # (incl. "consensus ingested it concurrently") must leave
            # this node able to SERVE the EC, or a future joiner stalls
            # on "peer omitted extended commit"
            if ec_bytes and not self.block_store.load_extended_commit(h):
                self.block_store.save_extended_commit(h, ec_bytes)
            parts = parts_by_idx.get(i)
            if parts is None:
                parts = self._canonical_parts(blk, nxt)
            if self.ingestor is not None:
                # fork: adaptive sync — pipeline the verified block
                # straight into the consensus state machine. The
                # ingestor applies the block and returns the post-apply
                # state so subsequent window validation isn't stale.
                if blk.height < self.ingestor.rs.height:
                    # consensus ingested it concurrently (catch-up)
                    self.state = self.ingestor.state
                    self.pool.pop_request()
                    self.blocks_applied += 1
                    applied += 1
                    continue
                try:
                    self.state = self.ingestor.ingest_verified_block(
                        blk, parts, nxt.last_commit
                    )
                except ValueError:
                    # consensus is mid-commit at this height; let it
                    # finish and resume on the next pass
                    break
            else:
                # usually persisted by the window-batch flush above
                # (or an earlier pass); blocks at/behind an EC
                # decision made during THIS loop (e.g. a tolerated
                # bare apply) were not staged — persist individually
                if self.block_store.height() < h:
                    self.block_store.save_block(
                        blk, parts, nxt.last_commit
                    )
                self.state = self.block_exec.apply_verified_block(
                    self.state, bid, blk
                )
            if h in self._ec_misses:
                del self._ec_misses[h]
                self.pool.clear_exclusions(h)
            self.pool.pop_request()
            self.blocks_applied += 1
            applied += 1
        else:
            # every job applied without a redo/ban/ingest break: the
            # pre-dispatched next-window handle stays valid for reuse
            # on the next pass (subject to the key re-check). On ANY
            # break the handle is dropped — its blocks may be
            # refetched or the valset may have moved.
            self._inflight = pre
        if pre is not None and self._inflight is not pre:
            self.pipeline_stats["discarded"] += 1
        return applied

    def _observe_window(self, applied: int, wall_s: float) -> None:
        """Per-window throughput: a counter event on the trace
        timeline + the live value the Prometheus gauge reads."""
        if applied <= 0 or wall_s <= 0:
            return
        bps = applied / wall_s
        self.last_window_bps = bps
        self.tracer.counter(
            "blocksync.window_blocks_per_s", round(bps, 1),
            tid="blocksync",
        )

    def _build_jobs(self, window, vals_hash, max_jobs: int):
        """Verify jobs for the leading valset-constant prefix of
        ``window`` (block i verified by block i+1's last_commit,
        PeekTwoBlocks K-wide), plus a reuse key identifying the exact
        inputs BY CONTENT: the valset hash and every involved block's
        hash (the hash covers the header, whose last_commit_hash binds
        the commit the job verifies). Content keys make refetches safe
        — a replaced block hashes differently, so a pre-dispatched
        handle can never be replayed against different inputs, while a
        content-identical refetch may still reuse it."""
        jobs = []
        for i in range(min(len(window) - 1, max_jobs)):
            h, blk, peer = window[i]
            _, nxt, _ = window[i + 1]
            if blk.header.validators_hash != vals_hash:
                break
            bid = T.BlockID(
                blk.hash(),
                nxt.last_commit.block_id.part_set_header,
            )
            jobs.append(
                (self.state.validators, bid, h, nxt.last_commit)
            )
        key = (
            vals_hash,
            tuple(
                bytes(window[i][1].hash()) for i in range(len(jobs) + 1)
            )
            if jobs
            else (),
        )
        return jobs, key

    def _check_extended_commit(self, h, blk, peer):
        """When vote extensions are enabled at height h the peer SHOULD
        supply a valid extended commit with the block (reference
        blocksync/reactor.go:648): commit sigs verify against the
        valset, extension signatures verify per lane, and the payload
        binds to this block. Returns the raw bytes to persist (or None
        when extensions are disabled).

        A peer that simply LACKS the EC is distinguished from one that
        sent an invalid EC: an honest node may legitimately hold a
        block without its EC (e.g. it tolerated missing ECs itself
        while syncing before this fix existed, or pruned them), so a
        missing payload raises MissingExtendedCommit — retried without
        banning, and tolerated once EC_MISS_TOLERANCE distinct fetches
        came back bare (otherwise a network where no reachable peer
        holds the EC for one height would stall blocksync forever)."""
        enabled = self.state.consensus_params.vote_extensions_enabled(h)
        ec_bytes = getattr(blk, "_ec_bytes", None)
        if not enabled:
            return None  # ignore unsolicited payloads
        if not ec_bytes:
            raise MissingExtendedCommit(
                "peer omitted extended commit at extension-enabled "
                f"height {h}"
            )
        ec = codec.decode_extended_commit(ec_bytes)
        T.verify_extended_commit(
            self.state.chain_id,
            self.state.validators,
            blk.hash(),
            h,
            ec,
            cache=self.sig_cache,
            priority=T.PRIORITY_CATCHUP,
        )
        return ec_bytes
