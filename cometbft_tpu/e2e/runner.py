"""E2E runner (reference test/e2e/runner/): provision node homes from
a manifest, launch real OS processes, apply tx load over RPC, inject
perturbations (kill/restart, pause/resume), wait for the target
height, then assert network-wide agreement.

Usage:
    python -m cometbft_tpu.e2e.runner manifest.toml [--dir DIR]
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import types as T
from ..config.config import default_config, write_toml
from ..p2p.key import NodeKey
from ..privval.file_pv import FilePV
from ..types.genesis import GenesisDoc
from .manifest import Manifest, NodeSpec

BASE_PORT = 27000
# run()'s phase budgets beyond timeout_s: all-node convergence, then
# the post phase — perturbation-finish wait (<=30s), the gRPC
# broadcast check (<=40s client deadline), and the bulk block-interval
# benchmark (a handful of 5s-bounded RPCs). Tests derive their OUTER
# guard from these so the guard can never truncate a healthy run
# mid-phase.
CONVERGENCE_BUDGET_S = 120.0
POST_BUDGET_S = 120.0


@dataclass
class RunnerNode:
    spec: NodeSpec
    home: str
    p2p_port: int
    rpc_port: int
    grpc_port: int = 0
    node_id: str = ""
    proc: Optional[subprocess.Popen] = None
    started: bool = False

    @property
    def rpc(self) -> str:
        return f"http://127.0.0.1:{self.rpc_port}"


class Runner:
    def __init__(self, manifest: Manifest, base_dir: str,
                 base_port: int = BASE_PORT):
        self.m = manifest
        self.dir = base_dir
        self.nodes: Dict[str, RunnerNode] = {}
        port = base_port
        for name, spec in manifest.nodes.items():
            self.nodes[name] = RunnerNode(
                spec, os.path.join(base_dir, name), port, port + 1,
                grpc_port=port + 2,
            )
            port += 3
        self.failures: List[str] = []

    # --- provisioning -------------------------------------------------

    def setup(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)
        validators = []
        pvs = {}
        for name, rn in self.nodes.items():
            os.makedirs(os.path.join(rn.home, "config"), exist_ok=True)
            os.makedirs(os.path.join(rn.home, "data"), exist_ok=True)
            pv = FilePV.load_or_generate(
                os.path.join(rn.home, "config", "priv_validator_key.json"),
                os.path.join(rn.home, "data", "priv_validator_state.json"),
            )
            pvs[name] = pv
            nk = NodeKey.load_or_gen(
                os.path.join(rn.home, "config", "node_key.json")
            )
            rn.node_id = nk.node_id
            if rn.spec.mode == "validator":
                validators.append(T.Validator(pv.pub_key(), rn.spec.power))
        gen = GenesisDoc(chain_id=self.m.chain_id, validators=validators)
        peers = ",".join(
            f"{rn.node_id}@127.0.0.1:{rn.p2p_port}"
            for rn in self.nodes.values()
            # light nodes run only the proxy daemon — nothing ever
            # listens on their p2p port
            if rn.spec.mode != "light"
        )
        for name, rn in self.nodes.items():
            cfg = default_config(rn.home)
            cfg.base.moniker = name
            cfg.p2p.laddr = f"tcp://127.0.0.1:{rn.p2p_port}"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rn.rpc_port}"
            cfg.rpc.unsafe = True  # perturbations use the unsafe routes
            if rn.spec.grpc:
                cfg.rpc.grpc_laddr = f"tcp://127.0.0.1:{rn.grpc_port}"
                # commit-await must survive a perturbed, contended net
                # (kill/pause perturbations land around the same
                # heights the check runs at)
                cfg.rpc.timeout_broadcast_tx_commit_s = 30.0
            cfg.p2p.persistent_peers = ",".join(
                p for p in peers.split(",")
                if not p.startswith(rn.node_id)
            )
            cfg.blocksync.enable = rn.spec.block_sync or rn.spec.state_sync
            cfg.blocksync.adaptive_sync = rn.spec.adaptive_sync
            cfg.mempool.type_ = rn.spec.mempool
            cfg.base.db_backend = rn.spec.db
            cfg.consensus.timeout_commit_s = 0.2
            if rn.spec.state_sync:
                cfg.statesync.enable = True
                cfg.statesync.rpc_servers = [
                    f"127.0.0.1:{o.rpc_port}"
                    for o in self.nodes.values()
                    if o.spec.start_at == 0 and o.spec.name != name
                ][:2]
                cfg.statesync.trust_height = 1  # filled at start_at time
                cfg.statesync.discovery_time_s = 15.0
            write_toml(cfg, os.path.join(rn.home, "config", "config.toml"))
            with open(
                os.path.join(rn.home, "config", "genesis.json"), "w"
            ) as f:
                f.write(gen.to_json())
            if rn.spec.mode in ("full", "light", "seed"):
                os.remove(
                    os.path.join(
                        rn.home, "config", "priv_validator_key.json"
                    )
                )

    # --- process control ----------------------------------------------

    def _launch(self, rn: RunnerNode, extra_env=None, argv=None) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if extra_env:
            env.update(extra_env)
        if argv is None:
            if rn.spec.mode == "light":
                # every light launch path (initial + perturbation
                # restart) must go through _launch_light, which builds
                # the proxy argv with retries off the event loop — a
                # bare relaunch here would start a FULL node on the
                # light node's port
                raise RuntimeError(
                    "light nodes launch via _launch_light"
                )
            argv = [
                sys.executable, "-m", "cometbft_tpu",
                "--home", rn.home, "start",
            ]
        rn.proc = subprocess.Popen(
            argv,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            env=env,
            stdout=open(os.path.join(rn.home, "node.log"), "a"),
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        rn.started = True

    async def _launch_light(self, rn: RunnerNode) -> None:
        """Launch a light-mode node: the verifying RPC proxy daemon
        (reference e2e light-node dimension), trust-rooted at block 1
        of a REACHABLE full node, witnesses wired to the other full
        nodes, serving on the node's rpc_port — so every runner
        assertion (status polling, agreement at the target height)
        exercises the LIGHT-VERIFIED path for this node. Retried off
        the event loop: the anchor candidates may be mid-perturbation
        (killed/paused) when the start height arrives."""
        last_err = None
        for _ in range(10):
            try:
                argv = await asyncio.to_thread(self._light_argv, rn)
                self._launch(rn, argv=argv)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                last_err = e
                await asyncio.sleep(2.0)
                continue
            # SUPERVISE startup: the daemon's own trust-root fetch can
            # hit the anchor mid-perturbation and exit — a dead or
            # never-serving daemon retries with a freshly-chosen
            # anchor instead of silently failing convergence
            for _ in range(30):
                if rn.proc.poll() is not None:
                    last_err = RuntimeError(
                        "light daemon exited at startup "
                        f"rc={rn.proc.returncode}"
                    )
                    break
                h = await asyncio.to_thread(self._height, rn)
                if h >= 0:
                    return  # serving verified status
                await asyncio.sleep(0.5)
            else:
                last_err = RuntimeError(
                    "light daemon never served status"
                )
                try:
                    rn.proc.terminate()
                except ProcessLookupError:
                    pass
            rn.started = False
            await asyncio.sleep(1.0)
        self.failures.append(
            f"light node {rn.spec.name} never launched: {last_err!r}"
        )

    def _light_argv(self, rn: RunnerNode) -> list:
        full = [
            o
            for o in self.nodes.values()
            if o is not rn and o.started and o.spec.mode != "light"
        ]
        primary = None
        trust = None
        for cand in full:
            try:
                trust = self._rpc(cand, "block?height=1")
                primary = cand
                break
            except Exception:
                continue
        if primary is None:
            raise RuntimeError(
                "no reachable full node to anchor the light node"
            )
        witnesses = [o for o in full if o is not primary][:2]
        argv = [
            sys.executable, "-m", "cometbft_tpu", "light",
            self.m.chain_id,
            "-p", f"127.0.0.1:{primary.rpc_port}",
            "--trust-height", "1",
            "--trust-hash", trust["block_id"]["hash"].lower(),
            "--laddr", f"tcp://127.0.0.1:{rn.rpc_port}",
            "--dir", os.path.join(rn.home, "light"),
        ]
        if witnesses:
            argv += [
                "-w",
                ",".join(
                    f"127.0.0.1:{o.rpc_port}" for o in witnesses
                ),
            ]
        return argv

    def _peer_addrs(self, rn: RunnerNode) -> list:
        """Other nodes' id@host:port addresses (reconnect targets)."""
        return [
            f"{other.node_id}@127.0.0.1:{other.p2p_port}"
            for name, other in self.nodes.items()
            if other is not rn
            and other.started
            and other.spec.mode != "light"
        ]

    def _rpc(self, rn: RunnerNode, path: str, timeout: float = 3.0):
        with urllib.request.urlopen(
            f"{rn.rpc}/{path}", timeout=timeout
        ) as r:
            body = json.load(r)
        if "result" not in body:
            raise RuntimeError(body.get("error"))
        return body["result"]

    def _rpc_post(self, rn: RunnerNode, method: str, params: dict,
                  timeout: float = 3.0):
        req = urllib.request.Request(
            rn.rpc + "/",
            data=json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": method,
                 "params": params}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = json.load(r)
        if "result" not in body:
            raise RuntimeError(body.get("error"))
        return body["result"]

    def _height(self, rn: RunnerNode) -> int:
        try:
            return int(
                self._rpc(rn, "status")["sync_info"]["latest_block_height"]
            )
        except Exception:
            return -1

    def network_height(self) -> int:
        return max(
            (self._height(rn) for rn in self.nodes.values() if rn.started),
            default=-1,
        )

    async def _network_height(self) -> int:
        # a SIGSTOP'd node accepts TCP but never answers; keep the 3s
        # stalls off the event loop
        return await asyncio.to_thread(self.network_height)

    # --- phases -------------------------------------------------------

    async def run(self, timeout_s: float = 300.0) -> bool:
        deadline = time.monotonic() + timeout_s
        aux_tasks: List[asyncio.Task] = []
        # start genesis nodes (a start_at=0 LIGHT node anchors itself
        # once the chain reaches height 1 — the retrying launcher
        # absorbs the wait)
        for rn in self.nodes.values():
            if rn.spec.start_at == 0:
                if rn.spec.mode == "light":
                    aux_tasks.append(
                        asyncio.create_task(self._launch_light(rn))
                    )
                else:
                    self._launch(rn)
        load_task = (
            asyncio.create_task(self._load_routine())
            if self.m.load_tx_rate > 0
            else None
        )
        pert_tasks = [
            asyncio.create_task(self._perturb_routine(rn))
            for rn in self.nodes.values()
            if rn.spec.perturbations
        ]
        late = [
            rn for rn in self.nodes.values() if rn.spec.start_at > 0
        ]
        try:
            while time.monotonic() < deadline:
                h = await self._network_height()
                for rn in late[:]:
                    if h >= rn.spec.start_at:
                        if rn.spec.mode == "light":
                            aux_tasks.append(
                                asyncio.create_task(
                                    self._launch_light(rn)
                                )
                            )
                        else:
                            await asyncio.to_thread(
                                self._fill_trust, rn
                            )
                            self._launch(rn)
                        late.remove(rn)
                if h >= self.m.target_height:
                    break
                await asyncio.sleep(0.5)
            else:
                self.failures.append(
                    f"timed out below target height "
                    f"({self.network_height()}/{self.m.target_height})"
                )
            # light-node launches must FINISH before convergence is
            # judged (a still-retrying launch would silently exclude
            # the node from the all-nodes check)
            if aux_tasks:
                await asyncio.gather(*aux_tasks, return_exceptions=True)
            # wait for EVERY node (incl. late joiners) to converge —
            # pointless if the net never reached the target at all
            if not self.failures:
                conv_deadline = time.monotonic() + CONVERGENCE_BUDGET_S
                hs = {}
                while time.monotonic() < conv_deadline:
                    started = [
                        (n, rn)
                        for n, rn in self.nodes.items()
                        if rn.started
                    ]
                    heights = await asyncio.gather(
                        *(
                            asyncio.to_thread(self._height, rn)
                            for _, rn in started
                        )
                    )
                    hs = dict(zip((n for n, _ in started), heights))
                    if all(
                        h >= self.m.target_height for h in hs.values()
                    ):
                        break
                    await asyncio.sleep(0.5)
                else:
                    self.failures.append(
                        f"nodes failed to converge: {hs}"
                    )
            # drive the gRPC broadcast API AFTER convergence — and
            # after QUIESCING the perturbation/load routines: a
            # lagging perturbation poll could otherwise fire its kill
            # mid-BroadcastTx and turn an intended perturbation into a
            # spurious testnet failure
            if not self.failures:
                # let lagging perturbation routines FINISH (their height
                # polls can trail the chain by seconds; cancelling a
                # not-yet-fired evidence injection would fail the
                # evidence assertion), then quiesce everything before
                # the gRPC check so no kill can race the in-flight RPC
                if pert_tasks:
                    # generous: a lagging evidence routine may still be
                    # inside its RPC retry loop (serial 3s-timeout
                    # height polls under contention)
                    await asyncio.wait(pert_tasks, timeout=60.0)
                quiesce = [t for t in [load_task, *pert_tasks] if t]
                for t in quiesce:
                    t.cancel()
                await asyncio.gather(*quiesce, return_exceptions=True)
                await self._check_grpc_broadcast()
                await asyncio.to_thread(self._benchmark_intervals)
        finally:
            if load_task:
                load_task.cancel()
            for t in pert_tasks:
                t.cancel()
            for t in aux_tasks:
                t.cancel()
        self._check_agreement()
        if any(
            p.kind in ("evidence", "evidence_lca")
            for rn in self.nodes.values()
            for p in rn.spec.perturbations
        ):
            # off-loop: the bounded wait inside must not stall
            # cancelled tasks' cleanup
            await asyncio.to_thread(self._check_evidence_committed)
        return not self.failures

    def _check_evidence_committed(self) -> None:
        """Injected evidence must end up inside a committed block
        (reference e2e evidence assertion). Bounded WAIT, not a
        snapshot: a late injection (LCA retries until the chain is
        tall enough) can leave the evidence pending at the target
        height — consensus keeps producing blocks after the load
        stops, so the next proposal from a pool-holding validator
        commits it within a couple of heights."""
        if not getattr(self, "_evidence_injected", False):
            self.failures.append("evidence perturbation never injected")
            return
        rn = next(o for o in self.nodes.values() if o.started)
        scanned = 0
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            top = self._height(rn)
            ok_through = scanned
            for h in range(scanned + 1, top + 1):
                try:
                    blk = self._rpc(rn, f"block?height={h}")
                except Exception:
                    # transient fetch failure: do NOT advance past h —
                    # the next pass re-examines it
                    break
                if blk["block"]["evidence"]["evidence"]:
                    return
                ok_through = h
            scanned = ok_through
            # sync-only call path: this method runs in a worker thread
            # via asyncio.to_thread (see the caller) — a blocking
            # sleep here parks the worker, not the event loop
            time.sleep(1.0)
        self.failures.append("no committed block contains evidence")

    def _fill_trust(self, rn: RunnerNode) -> None:
        """Late statesync nodes need a live trust root."""
        if not rn.spec.state_sync:
            return
        src = next(
            o for o in self.nodes.values()
            if o.started and o.spec.start_at == 0
        )
        blk = self._rpc(src, "block?height=1")
        cfg_path = os.path.join(rn.home, "config", "config.toml")
        with open(cfg_path) as f:
            text = f.read()
        text = text.replace(
            'trust_hash = ""',
            f'trust_hash = "{blk["block_id"]["hash"].lower()}"',
        )
        with open(cfg_path, "w") as f:
            f.write(text)

    # --- load + perturbations -----------------------------------------

    async def _load_routine(self) -> None:
        import base64

        seq = 0
        interval = 1.0 / self.m.load_tx_rate
        targets = [
            rn for rn in self.nodes.values() if rn.spec.start_at == 0
        ]
        while True:
            rn = targets[seq % len(targets)]
            tx = base64.b64encode(
                b"load-%06d=v%d" % (seq, seq)
            ).decode()
            seq += 1
            try:
                # JSON-RPC POST: base64 '+'/'/' chars survive (GET
                # query strings decode '+' to space)
                await asyncio.to_thread(
                    self._rpc_post, rn, "broadcast_tx_sync",
                    {"tx": tx}, 2.0,
                )
            except asyncio.CancelledError:
                raise  # run teardown cancels the load routine
            except (OSError, ValueError):
                pass  # node restarting mid-perturbation; keep loading
            await asyncio.sleep(interval)

    def _benchmark_intervals(self) -> None:
        """Block-interval statistics over the run (reference
        test/e2e/runner/benchmark.go:15-50: mean/stddev/min/max of the
        header-time deltas), recorded on ``self.benchmark``. Headers
        come from the bulk ``blockchain`` endpoint (20 metas per call)
        of a GENESIS node — a statesync joiner lacks pre-snapshot
        blocks. Non-monotonic header-time pairs (possible under BFT
        time with clock skew) are counted and reported, not silently
        dropped."""
        import statistics

        rn = next(
            (
                r
                for r in self.nodes.values()
                if r.started and r.spec.start_at == 0
            ),
            None,
        )
        if rn is None:
            return
        times = {}
        lo, hi = 2, self.m.target_height
        h = hi
        while h >= lo:
            for attempt in (1, 2, 3):
                try:
                    res = self._rpc(
                        rn,
                        f"blockchain?minHeight={lo}&maxHeight={h}",
                        timeout=5.0,
                    )
                    break
                except Exception as e:
                    if attempt == 3:
                        # post-convergence RPC should answer; a
                        # silent skip would make the smoke test fail
                        # with an inexplicable missing benchmark
                        self.failures.append(
                            f"benchmark: blockchain RPC failed: {e!r}"
                        )
                        return
                    # sync-only call path: _benchmark_intervals runs
                    # in a worker thread via asyncio.to_thread — this
                    # retry backoff never touches the event loop
                    time.sleep(0.2)
            metas = res.get("block_metas") or []
            if not metas:
                self.failures.append(
                    f"benchmark: no block metas <= {h}"
                )
                return
            for meta in metas:
                times[int(meta["header"]["height"])] = int(
                    meta["header"]["time_ns"]
                )
            nxt = min(times) - 1
            if nxt >= h:  # floor not advancing (pruned store): stop
                break
            h = nxt
        seq = [times[k] for k in sorted(times)]
        deltas = [(b - a) / 1e9 for a, b in zip(seq, seq[1:])]
        mono = [d for d in deltas if d > 0]
        if len(mono) < 2:
            return
        self.benchmark = {
            "blocks": len(seq),
            "non_monotonic_intervals": len(deltas) - len(mono),
            "interval_mean_s": round(statistics.mean(mono), 3),
            "interval_stddev_s": round(statistics.pstdev(mono), 3),
            "interval_min_s": round(min(mono), 3),
            "interval_max_s": round(max(mono), 3),
        }
        print(f"block-interval benchmark: {self.benchmark}")

    async def _check_grpc_broadcast(self) -> None:
        """Black-box drive of the legacy gRPC broadcast API on every
        grpc-enabled node: Ping + one BroadcastTx with commit
        semantics (reference test/e2e exercises live RPC the same
        way). Runs post-convergence; a failure is a testnet
        failure."""
        targets = [
            rn
            for rn in self.nodes.values()
            if rn.spec.grpc and rn.started
        ]
        if not targets:
            return
        from ..rpc.grpc_api import GRPCBroadcastClient

        def drive(rn):
            cli = GRPCBroadcastClient(f"127.0.0.1:{rn.grpc_port}")
            try:
                cli.ping()
                res = cli.broadcast_tx(
                    b"grpc-%s=1" % rn.spec.name.encode(), timeout=40.0
                )
                if res["check_tx"]["code"] != 0 or res["tx_result"][
                    "code"
                ] != 0:
                    self.failures.append(
                        f"{rn.spec.name}: gRPC broadcast rejected {res}"
                    )
            except Exception as e:
                self.failures.append(
                    f"{rn.spec.name}: gRPC broadcast failed: {e!r}"
                )
            finally:
                cli.close()

        await asyncio.gather(
            *(asyncio.to_thread(drive, rn) for rn in targets)
        )

    async def _perturb_routine(self, rn: RunnerNode) -> None:
        for pert in sorted(rn.spec.perturbations, key=lambda p: p.height):
            while await self._network_height() < pert.height:
                await asyncio.sleep(0.3)
            if not rn.proc:
                continue
            if pert.kind == "kill":
                print(f"[perturb] SIGKILL {rn.spec.name}", flush=True)
                rn.proc.send_signal(signal.SIGKILL)
                rn.proc.wait()
                await asyncio.sleep(pert.restart_delay_s)
                print(f"[perturb] restart {rn.spec.name}", flush=True)
                if rn.spec.mode == "light":
                    # retried off the event loop; anchors may be
                    # mid-perturbation themselves
                    await self._launch_light(rn)
                else:
                    self._launch(rn)
            elif pert.kind == "pause":
                print(f"[perturb] SIGSTOP {rn.spec.name}", flush=True)
                rn.proc.send_signal(signal.SIGSTOP)
                await asyncio.sleep(pert.pause_s)
                print(f"[perturb] SIGCONT {rn.spec.name}", flush=True)
                rn.proc.send_signal(signal.SIGCONT)
            elif pert.kind == "disconnect":
                # drop all peers via the unsafe RPC (reference does
                # this at the docker network layer); reconnect by
                # dialing the net's persistent peers again
                print(f"[perturb] disconnect {rn.spec.name}", flush=True)
                try:
                    await asyncio.to_thread(
                        self._rpc, rn, "unsafe_disconnect_peers"
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    print(f"[perturb] disconnect failed: {e}", flush=True)
                    continue
                await asyncio.sleep(pert.disconnect_s)
                peers = ",".join(
                    f'"{p}"' for p in self._peer_addrs(rn)
                )
                print(f"[perturb] reconnect {rn.spec.name}", flush=True)
                try:
                    await asyncio.to_thread(
                        self._rpc, rn, f"dial_peers?peers=[{peers}]"
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    print(f"[perturb] reconnect failed: {e}", flush=True)
            elif pert.kind == "upgrade":
                # graceful stop, relaunch as a newer version, confirm
                # the restarted node REPORTS that version and rejoins
                # (reference runner/perturb.go:37: stop container,
                # start the -u image; here: same binary, bumped
                # CMT_NODE_VERSION)
                print(
                    f"[perturb] upgrade {rn.spec.name} -> "
                    f"{pert.upgrade_version}",
                    flush=True,
                )
                rn.proc.send_signal(signal.SIGTERM)
                try:
                    rn.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    rn.proc.send_signal(signal.SIGKILL)
                    rn.proc.wait()
                await asyncio.sleep(1.0)
                self._launch(
                    rn, extra_env={"CMT_NODE_VERSION": pert.upgrade_version}
                )
                for _ in range(40):
                    await asyncio.sleep(0.5)
                    try:
                        st = await asyncio.to_thread(self._rpc, rn, "status")
                        got = st["node_info"]["version"]
                        if got == pert.upgrade_version:
                            self._upgraded_ok = True
                            break
                    except asyncio.CancelledError:
                        raise
                    except (OSError, ValueError, KeyError):
                        continue  # node still rebooting; poll again
                else:
                    self.failures.append(
                        f"{rn.spec.name} never reported upgraded "
                        f"version {pert.upgrade_version}"
                    )
            elif pert.kind in ("evidence", "evidence_lca"):
                # byzantine-evidence injection through another node's
                # broadcast_evidence RPC (reference
                # test/e2e/runner/evidence.go:32): "evidence" = this
                # node's key equivocates (DuplicateVoteEvidence);
                # "evidence_lca" = a >1/3-power subset of the real
                # validator keys signs a lunatic fork
                # (LightClientAttackEvidence). Retried: on a loaded
                # host an RPC can time out transiently.
                inject = (
                    self._inject_lca_evidence
                    if pert.kind == "evidence_lca"
                    else self._inject_evidence
                )
                print(
                    f"[perturb] {pert.kind} from {rn.spec.name}",
                    flush=True,
                )
                last_err = None
                try:
                    for attempt in range(10):
                        try:
                            await asyncio.to_thread(inject, rn)
                            self._evidence_injected = True
                            break
                        except asyncio.CancelledError:
                            raise
                        except Exception as e:
                            last_err = e
                            print(
                                f"[perturb] evidence attempt {attempt} "
                                f"failed: {e}",
                                flush=True,
                            )
                            await asyncio.sleep(2.0)
                    else:
                        # record WHY so a 'never injected' assertion
                        # is diagnosable instead of a bare flag check
                        self.failures.append(
                            f"evidence injection exhausted retries: "
                            f"{last_err!r}"
                        )
                except asyncio.CancelledError:
                    # quiesce cancelled us mid-retry: still leave a
                    # diagnosable cause behind the flag check
                    self.failures.append(
                        "evidence injection cancelled mid-retry "
                        f"(last error: {last_err!r})"
                    )
                    raise

    def _inject_lca_evidence(self, rn: RunnerNode) -> None:
        """Craft a lunatic-fork LightClientAttackEvidence signed by a
        >1/3-power subset of the net's real validator keys (the runner
        owns every validator home) and submit it over another node's
        broadcast_evidence RPC — the e2e twin of the in-process attack
        in tests/test_byzantine.py. The receiving pool must re-derive
        the byzantine set, verify both commits, and gossip it into a
        block."""
        import base64
        import dataclasses
        import time as _time

        from ..evidence.types import LightClientAttackEvidence
        from ..light.types import LightBlock
        from ..privval.file_pv import FilePV
        from ..utils import codec
        from .. import types as T

        target = next(
            o for o in self.nodes.values() if o is not rn and o.started
        )
        h = self._height(target) - 1
        if h < 2:
            raise RuntimeError("chain too short for LCA evidence")
        com = self._rpc(target, f"commit?height={h}")
        header = codec.decode_header(
            base64.b64decode(com["header_b64"])
        )
        vs = codec.decode_validator_set(
            base64.b64decode(
                self._rpc(target, f"validators?height={h}")[
                    "validator_set_b64"
                ]
            )
        )
        common_vals = codec.decode_validator_set(
            base64.b64decode(
                self._rpc(target, f"validators?height={h - 1}")[
                    "validator_set_b64"
                ]
            )
        )
        pv_by_addr = {}
        for o in self.nodes.values():
            keyfile = os.path.join(
                o.home, "config", "priv_validator_key.json"
            )
            if o.spec.mode != "validator" or not os.path.exists(keyfile):
                continue
            pv = FilePV.load(
                keyfile,
                os.path.join(
                    o.home, "data", "priv_validator_state.json"
                ),
            )
            pv_by_addr[pv.pub_key().address()] = pv
        total = common_vals.total_voting_power()
        chosen, power = [], 0
        for v in sorted(vs.validators, key=lambda x: -x.voting_power):
            pv = pv_by_addr.get(v.address)
            if pv is None:
                continue
            chosen.append((v, pv))
            power += v.voting_power
            if power * 3 > total:
                break
        if not power * 3 > total:
            raise RuntimeError(
                "not enough validator keys for >1/3 power"
            )
        fvs = T.ValidatorSet([v for v, _ in chosen])
        forged = dataclasses.replace(
            header,
            app_hash=b"\x77" * 32,
            validators_hash=fvs.hash(),
            next_validators_hash=fvs.hash(),
        )
        fbid = T.BlockID(
            forged.hash(), T.PartSetHeader(1, forged.hash())
        )
        now = _time.time_ns()
        sigs = []
        for v, pv in chosen:
            vote = T.Vote(
                type_=T.PRECOMMIT,
                height=h,
                round=0,
                block_id=fbid,
                timestamp_ns=now,
                validator_address=v.address,
                validator_index=0,
            )
            sigs.append(
                T.CommitSig(
                    block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                    validator_address=v.address,
                    timestamp_ns=now,
                    signature=pv.priv_key.sign(
                        vote.sign_bytes(self.m.chain_id)
                    ),
                )
            )
        lb = LightBlock(
            header=forged,
            commit=T.Commit(h, 0, fbid, sigs),
            validator_set=fvs,
        )
        ev = LightClientAttackEvidence(
            conflicting_block=lb,
            common_height=h - 1,
            total_voting_power=total,
            timestamp_ns=now,
        )
        ev.byzantine_validators = ev.byzantine_from(common_vals)
        self._rpc_post(
            target,
            "broadcast_evidence",
            {"evidence": "0x" + ev.encode().hex()},
            5.0,
        )

    def _inject_evidence(self, rn: RunnerNode) -> None:
        import time as _time

        from ..evidence.types import DuplicateVoteEvidence
        from ..privval.file_pv import FilePV
        from .. import types as T

        pv = FilePV.load(
            os.path.join(rn.home, "config", "priv_validator_key.json"),
            os.path.join(rn.home, "data", "priv_validator_state.json"),
        )
        # equivocate at a recent committed height so receiving pools
        # can resolve the validator set
        target = next(
            o for o in self.nodes.values() if o is not rn and o.started
        )
        h = self._height(target)
        if h < 1:
            raise RuntimeError("no committed height yet")
        votes = []
        now = _time.time_ns()
        for tag in (b"\xaa", b"\xbb"):
            v = T.Vote(
                type_=T.PREVOTE,
                height=h,
                round=0,
                block_id=T.BlockID(tag * 32, T.PartSetHeader(1, tag * 32)),
                timestamp_ns=now,
                validator_address=pv.pub_key().address(),
                validator_index=0,  # receiving pool resolves by address
                signature=b"",
            )
            v.signature = pv.priv_key.sign(v.sign_bytes(self.m.chain_id))
            votes.append(v)
        ev = DuplicateVoteEvidence.from_votes(
            votes[0], votes[1], rn.spec.power, 0, now
        )
        self._rpc_post(
            target,
            "broadcast_evidence",
            {"evidence": "0x" + ev.encode().hex()},
            5.0,
        )

    # --- assertions ---------------------------------------------------

    def _check_agreement(self) -> None:
        """All nodes must agree on the block at target height."""
        target = self.m.target_height
        hashes = {}
        for name, rn in self.nodes.items():
            if not rn.started:
                continue
            try:
                res = self._rpc(rn, f"block?height={target}")
                hashes[name] = res["block_id"]["hash"]
            except Exception as e:
                self.failures.append(f"{name}: no block {target}: {e}")
        if len(set(hashes.values())) > 1:
            self.failures.append(f"HASH DISAGREEMENT at {target}: {hashes}")

    def stop(self) -> None:
        for rn in self.nodes.values():
            if rn.proc is not None:
                try:
                    rn.proc.send_signal(signal.SIGCONT)  # unfreeze
                    rn.proc.terminate()
                except ProcessLookupError:
                    pass
        for rn in self.nodes.values():
            if rn.proc is not None:
                try:
                    rn.proc.wait(timeout=5)
                except Exception:
                    rn.proc.kill()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="cometbft-tpu-e2e")
    ap.add_argument("manifest")
    ap.add_argument("--dir", default="/tmp/cometbft-e2e")
    ap.add_argument("--timeout", type=float, default=300.0)
    args = ap.parse_args(argv)
    m = Manifest.load(args.manifest)
    runner = Runner(m, args.dir)
    runner.setup()
    try:
        ok = asyncio.run(runner.run(args.timeout))
    finally:
        runner.stop()
    if ok:
        print(f"PASS: {len(m.nodes)} nodes converged at height "
              f">= {m.target_height}")
        return 0
    print("FAIL:")
    for f in runner.failures:
        print(f"  - {f}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
