"""Testnet manifests (reference test/e2e/pkg/manifest.go).

TOML schema:

    chain_id = "e2e-net"
    target_height = 20
    load_tx_rate = 5          # txs/sec across the net (0 = off)

    [node.validator0]         # any number of [node.X] tables
    mode = "validator"        # validator | full | seed | light (full = no key)
    power = 10
    start_at = 0              # join later (height); 0 = from genesis
    block_sync = false
    state_sync = false
    adaptive_sync = false
    mempool = "clist"         # clist | nop
    kill_at = 0               # perturbations: height to SIGKILL then restart
    pause_at = 0              # height to SIGSTOP for pause_s seconds
    pause_s = 3.0
    restart_delay_s = 2.0
    disconnect_at = 0         # height to drop all peers, reconnect after
    disconnect_s = 3.0        # how long to stay disconnected
"""

from __future__ import annotations

try:
    import tomllib
except ImportError:  # pragma: no cover - py<3.11: same-API backport
    import tomli as tomllib
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Perturbation:
    kind: str  # "kill" | "pause" | "disconnect" | "evidence" |
    #            "evidence_lca" | "upgrade"
    height: int
    pause_s: float = 3.0
    restart_delay_s: float = 2.0
    disconnect_s: float = 3.0
    upgrade_version: str = "0.2.0-upgrade"


@dataclass
class NodeSpec:
    name: str
    mode: str = "validator"
    power: int = 10
    start_at: int = 0
    block_sync: bool = False
    state_sync: bool = False
    adaptive_sync: bool = False
    mempool: str = "clist"
    db: str = "sqlite"  # sqlite | logdb (native engine) | memdb
    grpc: bool = False  # serve the legacy gRPC broadcast API
    perturbations: List[Perturbation] = field(default_factory=list)


@dataclass
class Manifest:
    chain_id: str = "e2e-net"
    target_height: int = 20
    load_tx_rate: float = 0.0
    nodes: Dict[str, NodeSpec] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Manifest":
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "Manifest":
        m = cls(
            chain_id=raw.get("chain_id", "e2e-net"),
            target_height=int(raw.get("target_height", 20)),
            load_tx_rate=float(raw.get("load_tx_rate", 0.0)),
        )
        for name, nd in (raw.get("node") or {}).items():
            spec = NodeSpec(
                name=name,
                mode=nd.get("mode", "validator"),
                power=int(nd.get("power", 10)),
                start_at=int(nd.get("start_at", 0)),
                block_sync=bool(nd.get("block_sync", False)),
                state_sync=bool(nd.get("state_sync", False)),
                adaptive_sync=bool(nd.get("adaptive_sync", False)),
                mempool=nd.get("mempool", "clist"),
                db=nd.get("db", "sqlite"),
                grpc=bool(nd.get("grpc", False)),
            )
            if nd.get("kill_at"):
                spec.perturbations.append(
                    Perturbation(
                        "kill",
                        int(nd["kill_at"]),
                        restart_delay_s=float(
                            nd.get("restart_delay_s", 2.0)
                        ),
                    )
                )
            if nd.get("pause_at"):
                spec.perturbations.append(
                    Perturbation(
                        "pause",
                        int(nd["pause_at"]),
                        pause_s=float(nd.get("pause_s", 3.0)),
                    )
                )
            if nd.get("disconnect_at"):
                spec.perturbations.append(
                    Perturbation(
                        "disconnect",
                        int(nd["disconnect_at"]),
                        disconnect_s=float(nd.get("disconnect_s", 3.0)),
                    )
                )
            if nd.get("upgrade_at"):
                # graceful stop + relaunch as a NEWER software version
                # (single-binary analog of the reference's docker-image
                # swap, testnet.go:62 PerturbationUpgrade +
                # runner/perturb.go:37)
                spec.perturbations.append(
                    Perturbation(
                        "upgrade",
                        int(nd["upgrade_at"]),
                        upgrade_version=nd.get(
                            "upgrade_version", "0.2.0-upgrade"
                        ),
                    )
                )
            if nd.get("evidence_at"):
                # this node's validator key equivocates: crafted
                # DuplicateVoteEvidence is injected via the
                # broadcast_evidence RPC (reference
                # test/e2e/runner/evidence.go:32)
                spec.perturbations.append(
                    Perturbation("evidence", int(nd["evidence_at"]))
                )
            if nd.get("evidence_lca_at"):
                # lunatic-fork LightClientAttackEvidence signed by a
                # >1/3-power subset of the net's validator keys
                # (runner._inject_lca_evidence)
                spec.perturbations.append(
                    Perturbation(
                        "evidence_lca", int(nd["evidence_lca_at"])
                    )
                )
            m.nodes[name] = spec
        if not m.nodes:
            raise ValueError("manifest has no nodes")
        for n in m.nodes.values():
            if n.mode == "light" and any(
                p.kind != "kill" for p in n.perturbations
            ):
                # the light daemon has no p2p/mempool/consensus to
                # pause/disconnect/upgrade/equivocate; only
                # kill+relaunch is meaningful (runner._launch_light)
                raise ValueError(
                    f"light node {n.name} supports only 'kill' "
                    "perturbations"
                )
        if not any(
            n.mode == "validator" and n.start_at == 0
            for n in m.nodes.values()
        ):
            raise ValueError("manifest needs a genesis validator")
        return m
