"""End-to-end multi-process test harness (reference test/e2e/)."""

from .manifest import Manifest, NodeSpec, Perturbation
from .runner import Runner

__all__ = ["Manifest", "NodeSpec", "Perturbation", "Runner"]
