"""Load generation + commit-latency reporting.

Reference analog: test/loadtime — a tm-load-test-based generator whose
txs embed their creation timestamp, plus a `report` tool that scans
committed blocks and turns tx timestamps into a latency distribution
(test/loadtime/README.md). Here both halves are one module driven over
the JSON-RPC client: `LoadGenerator.run()` pushes timestamped txs at a
target rate over N logical connections; `latency_report()` walks the
chain and aggregates per-tx commit latency.

Tx format (self-describing, kvstore-compatible key=value so the
universal fake app accepts it, like the reference's e2e app payloads):
b"load:" + seq(16 hex) + "=" + time_ns(19 digits) + ":" + random
padding to `tx_size` bytes.
"""

from __future__ import annotations

import asyncio
import math
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

TX_PREFIX = b"load:"


def make_tx(seq: int, tx_size: int = 256, now_ns: Optional[int] = None) -> bytes:
    body = b"%s%016x=%019d:" % (TX_PREFIX, seq, now_ns or time.time_ns())
    pad = tx_size - len(body)
    if pad > 0:
        body += os.urandom((pad + 1) // 2).hex().encode()[:pad]
    return body


def parse_tx(tx: bytes) -> Optional[int]:
    """Returns the embedded send time_ns, or None for non-load txs."""
    if not tx.startswith(TX_PREFIX):
        return None
    try:
        _, val = tx.split(b"=", 1)
        return int(val.split(b":", 1)[0])
    except (IndexError, ValueError):
        return None


@dataclass
class LoadResult:
    sent: int = 0
    accepted: int = 0
    rejected: int = 0
    duration_s: float = 0.0

    @property
    def send_rate(self) -> float:
        return self.sent / self.duration_s if self.duration_s else 0.0


class LoadGenerator:
    """Rate-controlled tx spammer (reference test/loadtime/cmd/load +
    runner/load.go): `connections` concurrent submitters sharing a
    target aggregate rate, each tx timestamped at send."""

    def __init__(
        self,
        client,  # rpc.client.HTTPClient (or anything with broadcast_tx_sync)
        rate: float = 100.0,  # txs/sec aggregate
        connections: int = 1,
        tx_size: int = 256,
    ):
        self.client = client
        self.rate = rate
        self.connections = connections
        self.tx_size = tx_size
        self._seq = 0

    async def run(self, duration_s: float) -> LoadResult:
        res = LoadResult()
        t0 = time.monotonic()
        interval = self.connections / self.rate

        async def submitter(ci: int) -> None:
            next_at = t0 + (ci / self.rate)
            while True:
                now = time.monotonic()
                if now >= t0 + duration_s:
                    return
                if now < next_at:
                    await asyncio.sleep(min(next_at - now, 0.05))
                    continue
                next_at += interval
                self._seq += 1
                tx = make_tx(self._seq, self.tx_size)
                res.sent += 1
                try:
                    r = await self.client.broadcast_tx_sync(tx)
                    if int(r.get("code", 0)) == 0:
                        res.accepted += 1
                    else:
                        res.rejected += 1
                except asyncio.CancelledError:
                    raise  # gather() cancellation must propagate
                except Exception:
                    res.rejected += 1

        await asyncio.gather(
            *(submitter(i) for i in range(self.connections))
        )
        res.duration_s = time.monotonic() - t0
        return res


@dataclass
class LatencyReport:
    """Per-tx commit latency distribution (reference
    test/loadtime/report: min/max/avg/stddev per experiment)."""

    count: int = 0
    min_s: float = 0.0
    max_s: float = 0.0
    mean_s: float = 0.0
    stddev_s: float = 0.0
    p50_s: float = 0.0
    p95_s: float = 0.0
    heights: int = 0
    # block interval stats (reference test/e2e/runner/benchmark.go)
    block_interval_mean_s: float = 0.0
    block_interval_max_s: float = 0.0

    def to_dict(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "min_s": round(self.min_s, 4),
            "max_s": round(self.max_s, 4),
            "mean_s": round(self.mean_s, 4),
            "stddev_s": round(self.stddev_s, 4),
            "p50_s": round(self.p50_s, 4),
            "p95_s": round(self.p95_s, 4),
            "heights": self.heights,
            "block_interval_mean_s": round(self.block_interval_mean_s, 4),
            "block_interval_max_s": round(self.block_interval_max_s, 4),
        }


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


async def latency_report(
    client, from_height: int, to_height: int
) -> LatencyReport:
    """Walk [from_height, to_height], matching each load-tx's embedded
    send time against its block's commit timestamp."""
    lats: List[float] = []
    block_times: List[int] = []
    for h in range(from_height, to_height + 1):
        blk = await client.block_decoded(h)
        block_times.append(blk.header.time_ns)
        for tx in blk.data.txs:
            sent_ns = parse_tx(tx)
            if sent_ns is not None:
                lats.append((blk.header.time_ns - sent_ns) / 1e9)
    rep = LatencyReport(heights=to_height - from_height + 1)
    if lats:
        lats.sort()
        rep.count = len(lats)
        rep.min_s = lats[0]
        rep.max_s = lats[-1]
        rep.mean_s = sum(lats) / len(lats)
        rep.stddev_s = math.sqrt(
            sum((x - rep.mean_s) ** 2 for x in lats) / len(lats)
        )
        rep.p50_s = _percentile(lats, 0.50)
        rep.p95_s = _percentile(lats, 0.95)
    if len(block_times) >= 2:
        gaps = [
            (b - a) / 1e9 for a, b in zip(block_times, block_times[1:])
        ]
        rep.block_interval_mean_s = sum(gaps) / len(gaps)
        rep.block_interval_max_s = max(gaps)
    return rep
