"""Randomized testnet manifest generator (reference
test/e2e/generator/generate.go + random.go).

Generates deterministic pseudo-random manifests from a seed, covering
the combination space: topology (single / quad / large), sync modes
(blocksync, adaptive ingest, statesync late joiners), storage backend
(sqlite / native logdb), mempool type, tx load, and perturbations
(kill/restart, pause, disconnect, evidence injection, upgrade). A seed
fully determines the manifest, so any failing generated net is
reproducible from its seed alone.
"""

from __future__ import annotations

import random
from typing import List

from .manifest import Manifest, NodeSpec, Perturbation

TOPOLOGIES = ("single", "quad", "large")
DBS = ("sqlite", "logdb")


def _perturb(rng: random.Random, spec: NodeSpec, target: int, is_val: bool):
    """At most one perturbation per node (keeps runs bounded)."""
    lo, hi = 3, max(4, target - 4)
    roll = rng.random()
    if roll < 0.15:
        spec.perturbations.append(
            Perturbation("kill", rng.randint(lo, hi), restart_delay_s=1.0)
        )
    elif roll < 0.30:
        spec.perturbations.append(
            Perturbation("pause", rng.randint(lo, hi), pause_s=2.0)
        )
    elif roll < 0.45:
        spec.perturbations.append(
            Perturbation(
                "disconnect", rng.randint(lo, hi), disconnect_s=2.0
            )
        )
    elif roll < 0.55 and is_val:
        # 60/40 split: duplicate-vote equivocation vs a lunatic-fork
        # light-client attack (both land as committed evidence + ABCI
        # misbehavior; the runner crafts each from the real validator
        # keys)
        kind = "evidence" if rng.random() < 0.6 else "evidence_lca"
        spec.perturbations.append(
            Perturbation(kind, rng.randint(lo, hi))
        )
    elif roll < 0.65:
        # graceful binary-swap restart (reference testnet.go:62
        # PerturbationUpgrade)
        spec.perturbations.append(
            Perturbation("upgrade", rng.randint(lo, hi))
        )


def generate_one(seed: int) -> Manifest:
    rng = random.Random(seed)
    topology = rng.choice(TOPOLOGIES)
    target = rng.randint(8, 14)
    m = Manifest(
        chain_id=f"gen-{seed}",
        target_height=target,
        load_tx_rate=rng.choice((0.0, 2.0, 5.0)),
    )

    n_vals = {"single": 1, "quad": 4, "large": 4}[topology]
    for i in range(n_vals):
        spec = NodeSpec(
            name=f"val{i}",
            mode="validator",
            power=rng.choice((10, 10, 10, 5, 20)),
            db=rng.choice(DBS),
            grpc=rng.random() < 0.35,
        )
        # a single-validator net must keep its only proposer alive
        if n_vals > 1:
            # evidence needs a second running node to receive it
            _perturb(rng, spec, target, is_val=n_vals > 2)
        m.nodes[spec.name] = spec

    if topology == "large":
        for j in range(rng.randint(1, 3)):
            late = rng.random() < 0.6
            spec = NodeSpec(
                name=f"full{j}",
                mode="full",
                start_at=rng.randint(4, 6) if late else 0,
                db=rng.choice(DBS),
                mempool=rng.choice(("clist", "nop")),
            )
            if late:
                spec.block_sync = True
                spec.adaptive_sync = rng.random() < 0.5
            else:
                _perturb(rng, spec, target, is_val=False)
            m.nodes[spec.name] = spec
        if rng.random() < 0.4:
            # a LIGHT node: the verifying RPC proxy daemon, trust-
            # rooted once the chain is a few blocks tall; the runner's
            # status/agreement assertions then exercise the light-
            # verified path end to end
            m.nodes["light0"] = NodeSpec(
                name="light0",
                mode="light",
                start_at=rng.randint(3, 5),
            )

    return m


def generate(seed: int, count: int = 1) -> List[Manifest]:
    """count manifests derived deterministically from one seed."""
    return [generate_one(seed * 1000 + k) for k in range(count)]
