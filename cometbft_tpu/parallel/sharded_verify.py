"""Multi-chip commit verification: shard_map over signature lanes.

One XLA program = the framework's full "step" for commit verification:

  1. each device runs the ed25519 verify kernel on its shard of the
     signature lanes (ops/ed25519, pure VPU work, no communication);
  2. each device computes a partial voting-power tally of its valid
     lanes (masked weighted sum);
  3. a single ``psum`` over the mesh axis reduces the tally on ICI;
  4. every device returns the quorum verdict (tally vs threshold) and
     the gathered per-lane verdict mask.

This mirrors the semantic of the reference's VerifyCommit
(types/validation.go:30: sum voting power of valid signatures for the
block, compare against 2/3 of total) — but the signature work is spread
over chips instead of one Go routine's batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..ops import ed25519 as ed
from .mesh import DATA_AXIS


def _local_step(msgs, lens, pks, rs, ss, powers, threshold):
    """Per-device: verify local lanes, tally weighted power, psum."""
    ok = ed._verify_core(msgs, lens, pks, rs, ss)
    # int32 on-device tally: the authoritative (arbitrary-precision)
    # tally is recomputed host-side in types/validation.py; this value
    # drives the fast-path quorum verdict for realistic powers.
    local_tally = jnp.sum(jnp.where(ok, powers, 0), dtype=jnp.int32)
    tally = jax.lax.psum(local_tally, DATA_AXIS)
    ok_all = jax.lax.all_gather(ok, DATA_AXIS, tiled=True)
    return tally > threshold, tally, ok_all


def make_sharded_core(mesh):
    """Lane-sharded ``_verify_core``: per-device ZIP-215 verdicts, no
    cross-device communication (the tally/quorum reduction lives in
    ``make_sharded_verifier``; the host path in types/validation.py does
    its own arbitrary-precision tally).

    This is the PRODUCTION seam: ``ops/ed25519.verify_batch`` (behind
    crypto/batch.TpuBatchVerifier — the reference's injectable
    BatchVerifier, types/validation.go:261-270) routes through this
    whenever more than one local device is visible, so every
    VerifyCommit* caller scales over the mesh transparently.
    """
    spec_lanes = P(None, DATA_AXIS)   # (bytes, N)
    spec_vec = P(DATA_AXIS)           # (N,)
    fn = shard_map(
        ed._verify_core,
        mesh=mesh,
        in_specs=(spec_lanes, spec_vec, spec_lanes, spec_lanes, spec_lanes),
        out_specs=spec_vec,
        check_rep=False,
    )
    return jax.jit(fn)


def make_sharded_verifier(mesh):
    """Build the jitted multi-chip verify step for a mesh.

    Input arrays are lane-sharded on their last axis; scalars replicated.

    The on-device tally is int32: callers must keep total voting power
    under 2^31 (the returned wrapper enforces this host-side before
    dispatch). The production path (types/validation.py) recomputes the
    authoritative tally host-side in arbitrary precision either way;
    this fast-path verdict exists for callers that want the quorum
    decision without a host round-trip per job.
    """
    spec_lanes = P(None, DATA_AXIS)   # (bytes/limbs, N)
    spec_vec = P(DATA_AXIS)           # (N,)

    fn = shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(
            spec_lanes,  # msgs (cap, N)
            spec_vec,    # lens
            spec_lanes,  # pks
            spec_lanes,  # rs
            spec_lanes,  # ss
            spec_vec,    # powers
            P(),         # threshold
        ),
        out_specs=(P(), P(), spec_vec),
        check_rep=False,
    )
    jitted = jax.jit(fn)

    def step(msgs, lens, pks, rs, ss, powers, threshold):
        import numpy as _np

        total = int(_np.asarray(powers, dtype=_np.int64).sum())
        if total >= 2**31:
            raise ValueError(
                "total voting power overflows the int32 device tally; "
                "use the host tally path (types/validation.py)"
            )
        return jitted(msgs, lens, pks, rs, ss, powers, threshold)

    return step
