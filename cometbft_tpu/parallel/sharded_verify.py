"""Multi-chip commit verification: shard_map over signature lanes.

Two composable sharded programs:

  1. ``make_sharded_core`` — each device runs the ed25519 precomp
     verify kernel on its shard of the signature lanes (ops/ed25519,
     pure VPU work, no communication). This is what the production
     ``verify_batch`` seam dispatches on multi-device hosts.
  2. ``make_quorum_reducer`` — weighted voting-power tally of the
     verdict lanes, reduced with a single ``psum`` over ICI, plus the
     quorum compare.

Together they mirror the reference's VerifyCommit semantics
(types/validation.go:30: sum voting power of valid signatures, compare
against 2/3 of total) — but the signature work is spread over chips
instead of one Go routine's batch, and the kernel graph compiles once
independently of the (cheap) communication step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:  # jax >= 0.8: top-level shard_map, check_rep renamed check_vma
    from jax import shard_map as _shard_map

    # default mirrors the jax.experimental.shard_map fallback (True) so
    # call sites behave identically across jax versions
    def shard_map(f, mesh, in_specs, out_specs, check_rep=True):
        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_rep,
        )

except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..ops import ed25519 as ed
from .mesh import DATA_AXIS


def make_sharded_core(mesh, mode="precomp"):
    """Lane-sharded verify kernel: per-device ZIP-215 verdicts, no
    cross-device communication (the tally/quorum reduction lives in
    ``make_quorum_reducer``; the host path in types/validation.py does
    its own arbitrary-precision tally). ``mode`` selects the kernel:
    "precomp" (host-expanded A, small per-device widths), "plain"
    (bulk widths), or "precomp_tuple" (pytree A — docs/PERF.md lever
    #6) — same width rule as single-device dispatch
    (ops/ed25519.PRECOMP_MAX_LANES).

    This is the PRODUCTION seam: ``ops/ed25519.verify_batch`` (behind
    crypto/batch.TpuBatchVerifier — the reference's injectable
    BatchVerifier, types/validation.go:261-270) routes through this
    whenever more than one local device is visible, so every
    VerifyCommit* caller scales over the mesh transparently.
    """
    spec_lanes = P(None, DATA_AXIS)     # (bytes, N)
    spec_limbs = P(None, None, DATA_AXIS)  # (4, 20, N)
    spec_vec = P(DATA_AXIS)             # (N,)
    if mode == "precomp":
        inner = ed._verify_core_precomp
        in_specs = (
            spec_lanes,  # msgs
            spec_vec,    # lens
            spec_limbs,  # precomputed A
            spec_lanes,  # pks
            spec_lanes,  # rs
            spec_lanes,  # ss
        )
    elif mode == "precomp_tuple":
        inner = ed._verify_core_precomp_tuple
        # pytree A: 4 components x NLIMBS separate (N,) leaves, each
        # lane-sharded — the spec mirrors the pytree structure
        from ..ops import fe25519 as fe

        a_specs = tuple(
            tuple(spec_vec for _ in range(fe.NLIMBS))
            for _ in range(4)
        )
        in_specs = (
            spec_lanes,  # msgs
            spec_vec,    # lens
            a_specs,     # A as tuple-of-limbs pytree
            spec_lanes,  # pks
            spec_lanes,  # rs
            spec_lanes,  # ss
        )
    else:
        inner = ed._verify_core
        in_specs = (
            spec_lanes,  # msgs
            spec_vec,    # lens
            spec_lanes,  # pks
            spec_lanes,  # rs
            spec_lanes,  # ss
        )
    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec_vec,
        check_rep=False,
    )
    return jax.jit(fn)


def make_quorum_reducer(mesh):
    """Tiny sharded step: weighted tally of verdict lanes + one psum
    over ICI + quorum compare. Composes with make_sharded_core so the
    expensive kernel graph compiles ONCE; the communication pattern
    (the part a multi-chip dryrun must prove) compiles in seconds.

    The on-device tally is int32: the returned wrapper enforces total
    voting power < 2^31 host-side before dispatch. The production path
    (types/validation.py) recomputes the authoritative tally host-side
    in arbitrary precision either way; this fast-path verdict exists
    for callers that want the quorum decision without a host round
    trip per job (reference VerifyCommit semantics,
    types/validation.go:30).
    """
    spec_vec = P(DATA_AXIS)

    def local(ok, powers, threshold):
        local_tally = jnp.sum(
            jnp.where(ok, powers, 0), dtype=jnp.int32
        )
        tally = jax.lax.psum(local_tally, DATA_AXIS)  # rides ICI
        return tally > threshold, tally, ok

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec_vec, spec_vec, P()),
        out_specs=(P(), P(), spec_vec),
        check_rep=False,
    )
    jitted = jax.jit(fn)

    def step(ok, powers, threshold):
        import numpy as _np

        total = int(_np.asarray(powers, dtype=_np.int64).sum())
        if total >= 2**31:
            raise ValueError(
                "total voting power overflows the int32 device tally; "
                "use the host tally path (types/validation.py)"
            )
        return jitted(ok, powers, threshold)

    return step
