"""Device mesh helpers: the framework's ICI-scaling axis.

The reference scales commit verification with CPU batch verification
(types/validation.go:261) — one core, SIMD lanes. The TPU-native
equivalent shards signature lanes across a device mesh: each chip
verifies its slice, and the weighted voting-power tally rides ICI as an
``psum``. Consensus networking between hosts stays on DCN (p2p layer);
ICI carries only the crypto data parallelism (SURVEY.md §2.2).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "sig"  # signature-lane data parallelism


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (DATA_AXIS,))
