"""Transports: TCP (production) and in-memory (tests).

Parity with reference p2p/transport.go:137-306 (MultiplexTransport):
accept/dial a raw stream, upgrade it with the secret-connection
handshake, verify the proven identity, then exchange NodeInfo. The
in-memory transport runs the EXACT same upgrade path over a
socketpair, so tests exercise the full encryption/auth stack without
touching the network (reference analog: p2p/test_util.go).
"""

from __future__ import annotations

import asyncio
import socket
import struct
from typing import Callable, Dict, Optional, Tuple

from ..utils.tasks import spawn
from .conn.secret_connection import SecretConnection
from .key import NodeKey, node_id_from_pubkey
from .node_info import NodeInfo

HANDSHAKE_TIMEOUT_S = 10.0


class TransportError(Exception):
    pass


async def _exchange_node_info(
    sconn: SecretConnection, our_info: NodeInfo
) -> NodeInfo:
    """Length-prefixed NodeInfo swap inside the encrypted channel."""
    enc = our_info.encode()
    await sconn.write_msg(struct.pack(">I", len(enc)) + enc)
    hdr = await sconn.read_chunk()
    (n,) = struct.unpack(">I", hdr[:4])
    if n > 1 << 20:
        raise TransportError("oversized node info")
    buf = hdr[4:]
    while len(buf) < n:
        buf += await sconn.read_chunk()
    return NodeInfo.decode(buf[:n])


async def upgrade(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    node_key: NodeKey,
    our_info: NodeInfo,
    expected_id: Optional[str] = None,
) -> Tuple[SecretConnection, NodeInfo]:
    """Secret-connection handshake + identity check + NodeInfo swap."""
    sconn = await SecretConnection.handshake(
        reader, writer, node_key.priv_key, timeout=HANDSHAKE_TIMEOUT_S
    )
    proven_id = node_id_from_pubkey(sconn.remote_pubkey)
    if expected_id is not None and proven_id != expected_id:
        sconn.close()
        raise TransportError(
            f"dialed {expected_id} but peer proved {proven_id}"
        )
    their_info = await asyncio.wait_for(
        _exchange_node_info(sconn, our_info), HANDSHAKE_TIMEOUT_S
    )
    if their_info.node_id != proven_id:
        sconn.close()
        raise TransportError("node info ID does not match proven identity")
    try:
        our_info.compatible_with(their_info)
    except ValueError as e:
        sconn.close()
        raise TransportError(str(e))
    return sconn, their_info


class TCPTransport:
    """listen() + accept stream; dial(). Produces upgraded
    (SecretConnection, NodeInfo, conn_str) triples."""

    def __init__(self, node_key: NodeKey, node_info: NodeInfo,
                 fuzz_config=None):
        self.node_key = node_key
        self.node_info = node_info
        self._server: Optional[asyncio.AbstractServer] = None
        self.accept_queue: asyncio.Queue = asyncio.Queue(64)
        # network fault injection (reference p2p/fuzz.go via config
        # FuzzConnConfig); None/disabled = passthrough
        self.fuzz_config = fuzz_config

    @property
    def listen_addr(self) -> str:
        if self._server is None or not self._server.sockets:
            return ""
        host, port = self._server.sockets[0].getsockname()[:2]
        return f"{host}:{port}"

    async def listen(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        self._server = await asyncio.start_server(
            self._on_accept, host or "0.0.0.0", int(port)
        )
        self.node_info.listen_addr = self.listen_addr

    async def _on_accept(self, reader, writer):
        peername = writer.get_extra_info("peername")
        try:
            sconn, their_info = await upgrade(
                reader, writer, self.node_key, self.node_info
            )
        except asyncio.CancelledError:
            writer.close()
            raise
        except Exception:
            try:
                writer.close()
            except Exception:
                pass
            return
        from .fuzz import maybe_fuzz

        await self.accept_queue.put(
            (
                maybe_fuzz(sconn, self.fuzz_config),
                their_info,
                f"{peername[0]}:{peername[1]}",
            )
        )

    async def accept(self):
        return await self.accept_queue.get()

    async def dial(
        self, addr: str, expected_id: Optional[str] = None
    ):
        host, _, port = addr.rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        sconn, their_info = await upgrade(
            reader, writer, self.node_key, self.node_info, expected_id
        )
        from .fuzz import maybe_fuzz

        return maybe_fuzz(sconn, self.fuzz_config), their_info, addr

    async def close(self) -> None:
        if self._server:
            self._server.close()
            # close conns nobody consumed, else (py3.12+) wait_closed
            # blocks until every accepted transport is closed
            while not self.accept_queue.empty():
                sconn, _, _ = self.accept_queue.get_nowait()
                sconn.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 1.0)
            except asyncio.TimeoutError:
                pass


class MemoryTransport:
    """In-process transport hub: dial by node ID, backed by OS
    socketpairs so the full secret-connection path runs.

    ``link_hook`` is the pluggable fault plane (chaos/links.LinkTable
    satisfies it): an object with ``allow_dial(src_id, dst_id) ->
    bool`` consulted before a dial, and ``wrap(sconn, src_id, dst_id)
    -> conn`` applied to each side of an established connection so
    per-(src, dst) faults (partition, loss, latency, duplication,
    reordering) land on live links. ``None`` = passthrough."""

    _hubs: Dict[str, "MemoryTransport"] = {}

    def __init__(
        self,
        node_key: NodeKey,
        node_info: NodeInfo,
        network: str = "mem",
        link_hook=None,
    ):
        self.node_key = node_key
        self.node_info = node_info
        self.accept_queue: asyncio.Queue = asyncio.Queue(64)
        self._network = network
        self._addr = f"mem://{node_key.node_id}"
        self.link_hook = link_hook
        MemoryTransport._hubs[node_key.node_id] = self

    @property
    def listen_addr(self) -> str:
        return self._addr

    async def listen(self, addr: str = "") -> None:
        self.node_info.listen_addr = self._addr

    async def accept(self):
        return await self.accept_queue.get()

    async def dial(self, addr: str, expected_id: Optional[str] = None):
        target_id = addr.replace("mem://", "")
        our_id = self.node_key.node_id
        hub = MemoryTransport._hubs.get(target_id)
        if hub is None:
            raise TransportError(f"no in-memory node {target_id}")
        if self.link_hook is not None and not self.link_hook.allow_dial(
            our_id, target_id
        ):
            raise TransportError(
                f"link {our_id[:8]}->{target_id[:8]} partitioned"
            )
        a, b = socket.socketpair()
        a.setblocking(False)
        b.setblocking(False)
        r1, w1 = await asyncio.open_connection(sock=a)
        r2, w2 = await asyncio.open_connection(sock=b)

        async def remote_side():
            try:
                sconn, info = await upgrade(
                    r2, w2, hub.node_key, hub.node_info
                )
                if hub.link_hook is not None:
                    # the hub's writes traverse the target->us link
                    sconn = hub.link_hook.wrap(sconn, target_id, our_id)
                await hub.accept_queue.put(
                    (sconn, info, f"mem://{our_id}")
                )
            except asyncio.CancelledError:
                w2.close()
                raise
            except Exception:
                try:
                    w2.close()
                except Exception:
                    pass

        task = spawn(remote_side(), name="mem-transport-accept")
        try:
            sconn, their_info = await upgrade(
                r1, w1, self.node_key, self.node_info, expected_id or target_id
            )
        except Exception:
            task.cancel()
            raise
        await task
        if self.link_hook is not None:
            sconn = self.link_hook.wrap(sconn, our_id, target_id)
        return sconn, their_info, addr

    async def close(self) -> None:
        MemoryTransport._hubs.pop(self.node_key.node_id, None)
        # drain conns nobody consumed: an in-process restart must not
        # inherit stale half-open connections from its previous life
        while not self.accept_queue.empty():
            sconn, _, _ = self.accept_queue.get_nowait()
            sconn.close()
