"""Node identity (reference p2p/key.go).

A node's ID is the hex of the first 20 bytes of SHA-256 over its
ed25519 public key — the same derivation the reference uses for
crypto addresses (tmhash.SumTruncated), so IDs are verifiable from
the pubkey learned during the secret-connection handshake.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass

from ..crypto.keys import Ed25519PrivKey, PubKey

ID_BYTE_LENGTH = 20


def node_id_from_pubkey(pub: PubKey) -> str:
    return hashlib.sha256(bytes(pub)).digest()[:ID_BYTE_LENGTH].hex()


@dataclass
class NodeKey:
    priv_key: Ed25519PrivKey

    @property
    def node_id(self) -> str:
        return node_id_from_pubkey(self.priv_key.pub_key())

    @classmethod
    def generate(cls) -> "NodeKey":
        return cls(Ed25519PrivKey.generate())

    # --- persistence (node_key.json, reference p2p/key.go:60) ---------

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            return cls.load(path)
        nk = cls.generate()
        nk.save(path)
        return nk

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            d = json.load(f)
        seed = bytes.fromhex(d["priv_key"])[:32]
        return cls(Ed25519PrivKey(seed))

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {
                    "id": self.node_id,
                    "priv_key": bytes(self.priv_key).hex(),
                },
                f,
                indent=2,
            )
