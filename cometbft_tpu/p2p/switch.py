"""Switch: owns transport, peers, and reactors (reference p2p/switch.go).

Responsibilities (mirroring the reference):
- accept loop: upgraded inbound conns -> add_peer
- dial_peers_async with persistent-peer redial handed to the
  self-healing ReconnectPlane (p2p/reconnect.py): budgeted full-jitter
  fast lane + never-give-up slow-lane sweep (the reference's
  reconnectToPeer gave up after a finite budget; ours cannot — a
  healed partition must always converge)
- incarnation-safe dial dedup: duplicate conns are resolved on
  (node id, incarnation) — a restarted remote's fresh dial EVICTS the
  zombie entry (sync abort, the PR 10 floor) instead of being
  dup-discarded against it, and simultaneous cross-dials resolve
  deterministically (the conn whose dialer has the lower node id wins
  on both ends; the loser's conn is closed synchronously)
- channel routing: every complete MConnection message is dispatched to
  the reactor that registered its channel
- stop_peer_for_error: the single choke point reactors use to drop a
  misbehaving peer (and everything re-routes through reconnect logic)
- max peer caps + dedup by node ID.
"""

from __future__ import annotations

import asyncio
import time
import traceback
from typing import Dict, List, Optional

from ..analysis.runtime import get_sanitizer
from ..trace import NOOP as TRACE_NOOP
from ..utils.log import get_logger
from ..utils.tasks import spawn
from . import tracewire
from .node_info import ChannelDescriptor, NodeInfo
from .peer import Peer
from .reactor import Reactor
from .reconnect import ReconnectPlane

_log = get_logger("p2p")

DEFAULT_MAX_PEERS = 50
# health connectivity verdict default: degraded below this many peers
# (only once the node has evidence it is MEANT to be connected)
DEFAULT_MIN_PEERS = 1
# duplicate-conn resolution: a conn OLDER than this facing a fresh
# opposite-dialer conn is not in a simultaneous dial race — the fresh
# conn is a redial against our (one-sided-dead) entry and wins
CROSS_DIAL_WINDOW_S = 5.0


class Switch:
    def __init__(
        self,
        transport,
        node_info: NodeInfo,
        max_peers: int = DEFAULT_MAX_PEERS,
        mconn_config: Optional[dict] = None,
        use_autopool: bool = False,
        reconnect_config: Optional[dict] = None,
    ):
        # fork feature: reactor messages can be drained by an
        # auto-scaling worker pool (reference lp2p/reactor_set.go +
        # internal/autopool) instead of inline dispatch
        self._autopool = None
        self._use_autopool = use_autopool
        self.transport = transport
        self.node_info = node_info
        self.reactors: Dict[str, Reactor] = {}
        self.chan_to_reactor: Dict[int, Reactor] = {}
        self.channel_descs: List[ChannelDescriptor] = []
        self._chan_caps: Dict[int, int] = {}
        self.peers: Dict[str, Peer] = {}
        # loop-affinity guard (analysis/runtime.py): the peer map
        # is mutated only on the switch's event loop
        self._sanitizer = get_sanitizer()
        self.persistent_addrs: Dict[str, str] = {}  # id -> addr
        self.banned: set = set()
        self.max_peers = max_peers
        self.mconn_config = mconn_config or {}
        self._accept_task: Optional[asyncio.Task] = None
        self._stopped = False
        # self-healing connectivity plane (p2p/reconnect.py): owns all
        # persistent-peer redial; Lp2pSwitch inherits it unchanged
        self.reconnect = ReconnectPlane(self, **(reconnect_config or {}))
        # PEX address book, set by node wiring when PEX is on: the
        # reconnect plane consults it for re-learned addresses and
        # records dial failures into it
        self.addr_book = None
        # health connectivity verdict floor (rpc/core.health)
        self.min_peers = DEFAULT_MIN_PEERS
        # tracing plane (trace/): node wiring swaps in the per-node
        # tracer; peer-count changes land as counter events
        self.tracer = TRACE_NOOP
        # cross-node causal tracing (p2p/tracewire.py): when the node
        # wiring enables stamping, outbound consensus/mempool/
        # blocksync messages carry a trace context and every stamped
        # receive records a correlated instant. None = fully off
        # (one attribute check per send, startswith per receive).
        self.stamper = None

    # --- reactor registry ---------------------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        self.reactors[name] = reactor
        for desc in reactor.get_channels():
            if desc.chan_id in self.chan_to_reactor:
                raise ValueError(
                    f"channel {desc.chan_id:#x} claimed twice"
                )
            self.chan_to_reactor[desc.chan_id] = reactor
            self.channel_descs.append(desc)
            self._chan_caps[desc.chan_id] = desc.max_msg_size
            self.node_info.channels.append(desc.chan_id)
        reactor.set_switch(self)
        return reactor

    def reactor(self, name: str) -> Optional[Reactor]:
        return self.reactors.get(name)

    # --- lifecycle ----------------------------------------------------

    async def start(self) -> None:
        self._sanitizer.tag("p2p.switch.peers")
        if self._use_autopool:
            from ..utils.autopool import AutoPool

            self._autopool = AutoPool(min_workers=2, max_workers=16)
            self._autopool.start()
        for r in self.reactors.values():
            await r.start()
        self._accept_task = asyncio.create_task(self._accept_routine())
        self.reconnect.start()

    async def stop(self) -> None:
        # every await is bounded (ASY110): one wedged reactor/peer/
        # transport must not hang the node's whole stop chain — the
        # outer Node._shutdown stage would catch it, but per-plane
        # bounds keep the blast radius to the plane that hung
        self._stopped = True
        if self._autopool is not None:
            try:
                await asyncio.wait_for(self._autopool.stop(), 5.0)
            except asyncio.TimeoutError:
                pass
        if self._accept_task:
            self._accept_task.cancel()
        self.reconnect.stop()
        for r in self.reactors.values():
            try:
                # 12s: strictly ABOVE the largest per-plane bound a
                # reactor stop carries internally (mempool/blocksync
                # budget their sub-planes at 10s) — an inner bound
                # must stay reachable or its post-wait cleanup is
                # silently skipped
                await asyncio.wait_for(r.stop(), 12.0)
            except asyncio.CancelledError:
                raise
            except asyncio.TimeoutError:
                _log.error(
                    "reactor stop exceeded its budget, abandoning",
                    reactor=type(r).__name__,
                )
            except Exception:
                traceback.print_exc()
        for p in list(self.peers.values()):
            try:
                # 9s: strictly above Peer.stop's internal 7s bound
                # (same reachability rule as the reactor bound above)
                await asyncio.wait_for(self._remove_peer(p, None), 9.0)
            except asyncio.TimeoutError:
                # the fd must still die (zombie-conn rejoin wedge)
                try:
                    p.abort()
                except Exception:
                    pass
        try:
            await asyncio.wait_for(self.transport.close(), 5.0)
        except asyncio.TimeoutError:
            pass

    def abort(self) -> None:
        """Synchronous last-resort teardown (ShutdownGuard escalation):
        when the graceful ``stop()`` stage was cancelled/abandoned past
        its budget, every remaining connection must STILL die — a conn
        left open past shutdown is a zombie its remote keeps treating
        as a live peer, so it dup-discards the restarted node's fresh
        dials and the node can never rejoin (the liveness wedge the
        scenario matrix surfaced under full-suite contention). Never
        awaits; reactors get their sync remove_peer so gossip tasks
        are cancelled, not left erroring against dead fds."""
        self._stopped = True
        if self._accept_task:
            self._accept_task.cancel()
        self.reconnect.stop()
        for p in list(self.peers.values()):
            for r in self.reactors.values():
                try:
                    r.remove_peer(p, None)
                except Exception:
                    pass
            try:
                p.abort()
            except Exception:
                pass
        self.peers.clear()
        self.tracer.counter("p2p.peers", 0, tid="p2p")
        spawn(
            self._close_transport_best_effort(),
            name="switch-abort-transport",
        )

    async def _close_transport_best_effort(self) -> None:
        try:
            await asyncio.wait_for(self.transport.close(), 5.0)
        except asyncio.CancelledError:
            raise
        except Exception:
            pass

    # --- accept / dial ------------------------------------------------

    async def _accept_routine(self) -> None:
        while not self._stopped:
            try:
                sconn, their_info, conn_str = await self.transport.accept()
            except asyncio.CancelledError:
                raise
            except Exception:
                traceback.print_exc()
                await asyncio.sleep(0.1)
                continue
            if (
                their_info.node_id in self.banned
                or their_info.node_id == self.node_info.node_id
            ):
                self._discard_conn(sconn)
                continue
            existing = self.peers.get(their_info.node_id)
            if existing is not None:
                # incarnation-safe dedup: the duplicate may be the
                # LIVE conn (restarted remote, cross-dial winner)
                if self._new_conn_wins(existing, their_info, inbound=True):
                    self._evict_peer_sync(
                        existing,
                        ConnectionError("superseded by newer conn"),
                    )
                else:
                    self._discard_conn(sconn)
                    continue
            elif len(self.peers) >= self.max_peers:
                self._discard_conn(sconn)
                continue
            self._make_peer(sconn, their_info, conn_str, outbound=False)

    async def dial_peer(
        self, addr: str, peer_id: Optional[str] = None, persistent: bool = False
    ) -> Optional[Peer]:
        """addr forms: "id@host:port", "host:port", "mem://id"."""
        if "@" in addr:
            peer_id, _, addr = addr.partition("@")
        if peer_id == self.node_info.node_id:
            raise ValueError("cannot dial self")
        if peer_id and (peer_id in self.peers or peer_id in self.banned):
            return self.peers.get(peer_id)
        if persistent and peer_id:
            self.persistent_addrs[peer_id] = addr
        try:
            sconn, their_info, conn_str = await self.transport.dial(
                addr, peer_id
            )
        except Exception as e:
            if persistent and peer_id:
                # hand the retry to the self-healing plane (counted;
                # never given up on)
                self.reconnect.note_dial_failure(peer_id)
            raise e
        if their_info.node_id == self.node_info.node_id:
            self._discard_conn(sconn)
            raise ValueError("dialed own address (self-connection)")
        existing = self.peers.get(their_info.node_id)
        if existing is not None:
            if self._new_conn_wins(existing, their_info, inbound=False):
                self._evict_peer_sync(
                    existing,
                    ConnectionError("superseded by newer conn"),
                )
            else:
                self._discard_conn(sconn)
                return existing
        return self._make_peer(
            sconn, their_info, conn_str, outbound=True, persistent=persistent
        )

    def dial_peers_async(self, addrs: List[str], persistent: bool = False):
        return [
            asyncio.create_task(self._dial_ignore_err(a, persistent))
            for a in addrs
        ]

    async def _dial_ignore_err(self, addr: str, persistent: bool):
        try:
            await self.dial_peer(addr, persistent=persistent)
        except asyncio.CancelledError:
            raise
        except Exception:
            pass  # dial errors are expected; the reconnect plane owns
            # the retry (dial_peer already routed the failure there)

    # --- duplicate-conn resolution ------------------------------------

    def _new_conn_wins(
        self, existing: Peer, their_info, inbound: bool
    ) -> bool:
        """Deterministic duplicate resolution keyed on
        (node id, incarnation):

        - different incarnation → the registered peer is a previous
          life of the remote (its conn may be a zombie the abort floor
          has not reaped yet): the NEW conn always wins, so a
          restarted node's dials are never dup-discarded against a
          stale entry;
        - same incarnation, same dialer → a REDIAL: the origin only
          dials again because its end of the old conn is already dead
          (our side may not have processed the EOF yet), so the new
          conn wins — dup-discarding it would throw away the redial
          against a conn that is about to die anyway;
        - same incarnation, opposite dialers, EXISTING conn long
          established → also a redial: the remote's end of the old
          conn died one-sided (we have not noticed yet), so its fresh
          dial wins — the tiebreak below must not keep discarding
          legitimate redials in favor of a zombie until the pong
          timeout reaps it;
        - same incarnation, opposite dialers, both young →
          simultaneous cross-dial: the conn whose DIALER has the
          lower node id wins, evaluated identically on both ends
          (each end keeps the same one connection and closes the
          other synchronously)."""
        new_inc = getattr(their_info, "incarnation", "")
        old_inc = getattr(existing.node_info, "incarnation", "")
        if new_inc and old_inc and new_inc != old_inc:
            return True
        me = self.node_info.node_id
        them = their_info.node_id
        new_dialer = them if inbound else me
        old_dialer = me if existing.outbound else them
        if new_dialer == old_dialer:
            return True
        established = getattr(existing, "established_at", 0.0)
        if time.monotonic() - established > CROSS_DIAL_WINDOW_S:
            return True  # not a dial race: the remote REDIALED
        return new_dialer < old_dialer

    def _evict_peer_sync(self, peer: Peer, reason: Exception) -> None:
        """Synchronous removal of a duplicate-resolution loser: the
        conn must be DEAD before the replacement registers (never
        awaits — same floor as abort())."""
        if self._sanitizer.enabled:
            self._sanitizer.touch("p2p.switch.peers")
        if self.peers.get(peer.peer_id) is peer:
            del self.peers[peer.peer_id]
            self.tracer.counter("p2p.peers", len(self.peers), tid="p2p")
        _log.info(
            "evicted duplicate peer conn",
            peer=peer.peer_id[:12],
            reason=str(reason),
            outbound=peer.outbound,
        )
        for r in self.reactors.values():
            try:
                r.remove_peer(peer, reason)
            except Exception:
                traceback.print_exc()
        try:
            peer.abort()
        except Exception:
            pass

    # --- peer management ----------------------------------------------

    def _discard_conn(self, sconn) -> None:
        """Close an upgraded connection rejected before peer
        registration; subclasses release admission resources here."""
        sconn.close()

    def _register_peer(self, peer) -> None:
        """Shared tail of peer construction: register, start, announce
        to reactors, feed the self-healing plane."""
        peer.established_at = time.monotonic()
        if self._sanitizer.enabled:
            self._sanitizer.touch("p2p.switch.peers")
        self.peers[peer.peer_id] = peer
        self.tracer.counter("p2p.peers", len(self.peers), tid="p2p")
        _log.info(
            "added peer",
            peer=peer.peer_id[:12],
            addr=peer.conn_str,
            outbound=peer.outbound,
            total=len(self.peers),
        )
        was_starving = self.reconnect.on_peer_connected(peer)
        if self.addr_book is not None and peer.node_info.listen_addr:
            self.addr_book.mark_good(
                peer.peer_id,
                f"{peer.peer_id}@{peer.node_info.listen_addr}",
            )
        peer.start()
        for r in self.reactors.values():
            try:
                r.add_peer(peer)
            except Exception:
                traceback.print_exc()
        if was_starving:
            # starvation exit: re-learn moved/healed addresses NOW —
            # a rejoining minority must not wait out the PEX crawl
            # interval to find where everyone went
            pex = self.reactors.get("pex")
            if pex is not None and hasattr(pex, "request_now"):
                pex.request_now(peer)

    def _make_peer(
        self, sconn, their_info, conn_str, outbound, persistent=False
    ) -> Peer:
        channels = [
            (d.chan_id, d.priority, d.max_msg_size)
            for d in self.channel_descs
        ]
        peer = Peer(
            sconn,
            their_info,
            conn_str,
            channels,
            on_receive=self._on_peer_msg,
            on_error=self._on_peer_error,
            outbound=outbound,
            persistent=persistent
            or their_info.node_id in self.persistent_addrs,
            mconn_config=self.mconn_config,
        )
        self._register_peer(peer)
        return peer

    def _on_peer_msg(self, chan_id: int, msg: bytes, peer: Peer) -> None:
        # cross-node tracing: peel an optional trace-context stamp
        # (tracewire) before channel dispatch, recording the
        # correlated receive instant. Decoding is ALWAYS on — stamped
        # traffic from tracing peers must interop with nodes whose own
        # stamping (or whole tracer) is off.
        if msg[:2] == tracewire.MAGIC:
            ctx, msg = tracewire.unstamp(msg)
            if ctx is not None and self.stamper is not None:
                self.stamper.on_receive(ctx, peer.peer_id)
        reactor = self.chan_to_reactor.get(chan_id)
        if reactor is None:
            self.stop_peer_for_error(
                peer, ValueError(f"msg on unclaimed channel {chan_id:#x}")
            )
            return
        if self._autopool is not None:
            if not self._autopool.submit(
                self._dispatch, reactor, chan_id, peer, msg
            ):
                # saturated pool: dispatch inline rather than dropping
                # (a lost vote/part can stall a consensus round)
                self._dispatch(reactor, chan_id, peer, msg)
            return
        self._dispatch(reactor, chan_id, peer, msg)

    def _dispatch(self, reactor, chan_id: int, peer: Peer, msg: bytes):
        try:
            reactor.receive(chan_id, peer, msg)
        except Exception as e:
            _log.error(
                "reactor receive failed, stopping peer",
                channel=f"{chan_id:#x}",
                peer=peer.peer_id[:12],
                err=repr(e),
            )
            traceback.print_exc()
            self.stop_peer_for_error(peer, e)

    def _on_peer_error(self, peer: Peer, exc: Exception) -> None:
        self.stop_peer_for_error(peer, exc)

    def stop_peer_for_error(self, peer: Peer, exc: Optional[Exception]):
        spawn(self._remove_peer(peer, exc, reconnect=True))

    async def stop_peer_gracefully(self, peer: Peer):
        await self._remove_peer(peer, None, reconnect=False)

    async def _remove_peer(self, peer, exc, reconnect=False) -> None:
        if self.peers.get(peer.peer_id) is not peer:
            return
        if self._sanitizer.enabled:
            self._sanitizer.touch("p2p.switch.peers")
        del self.peers[peer.peer_id]
        self.tracer.counter("p2p.peers", len(self.peers), tid="p2p")
        _log.info(
            "removed peer",
            peer=peer.peer_id[:12],
            err=repr(exc) if exc else "",
            total=len(self.peers),
        )
        for r in self.reactors.values():
            try:
                r.remove_peer(peer, exc)
            except Exception:
                traceback.print_exc()
        await peer.stop()
        if not self._stopped:
            self.reconnect.on_peer_removed(peer, had_error=reconnect)

    def ban_peer(self, peer_id: str) -> None:
        _log.info("banned peer", peer=peer_id[:12])
        self.banned.add(peer_id)
        self.reconnect.abandon(peer_id)  # the one sanctioned give-up
        p = self.peers.get(peer_id)
        if p:
            spawn(self._remove_peer(p, None))

    # --- broadcast / trace stamping -----------------------------------

    def enable_stamping(
        self, tracer, origin: str, outbound: bool = True
    ) -> None:
        """Turn on the cross-node tracing plane (node wiring).
        ``outbound=False`` ([instrumentation] trace_msg_stamp off)
        keeps receive-side correlation recording while this node's
        own sends go out unstamped."""
        self.stamper = tracewire.TraceStamper(tracer, origin, outbound)

    def stamp_msg(
        self,
        chan_id: int,
        msg: bytes,
        kind: str,
        height: int = 0,
        round_: int = -1,
        peer: str = "",
    ) -> bytes:
        """Wire form for a single traced send (the per-peer gossip
        routines): stamped when the stamping plane is on; otherwise
        the message unchanged — except a payload that happens to
        begin with the stamp magic, which is escaped either way
        (receive-side peel is ALWAYS on, so a raw magic-prefixed
        payload — e.g. an adversarial tx — would otherwise be
        mutated by the receiver)."""
        st = self.stamper
        if st is None or not st.outbound:
            return tracewire.encode_plain(
                msg, self._chan_caps.get(chan_id, 0)
            )
        return st.wrap(
            msg, kind, height=height, round_=round_,
            cap=self._chan_caps.get(chan_id, 0), peer=peer[:12],
        )

    def broadcast(
        self,
        chan_id: int,
        msg: bytes,
        tkind: Optional[str] = None,
        height: int = 0,
        round_: int = -1,
    ) -> None:
        """Send to every peer; with ``tkind`` set and stamping on, the
        message is stamped ONCE with a trace context (ISSUE 7: one
        encode per broadcast, one send instant carrying the fan-out).
        Unstamped broadcasts still escape a magic-prefixed payload
        (see ``stamp_msg``) — raw txs are attacker-shaped bytes."""
        st = self.stamper
        if st is not None and st.outbound and tkind is not None:
            msg = st.wrap(
                msg, tkind, height=height, round_=round_,
                cap=self._chan_caps.get(chan_id, 0),
                npeers=len(self.peers),
            )
        else:
            msg = tracewire.encode_plain(
                msg, self._chan_caps.get(chan_id, 0)
            )
        for p in list(self.peers.values()):  # bftlint: disable=ASY117 — flood fanout IS the protocol floor: one encode per broadcast, O(peers) enqueues of one shared bytes object; vote-aggregation relay (ROADMAP item 1) is the committee-scale answer
            p.try_send(chan_id, msg)

    def num_peers(self) -> int:
        return len(self.peers)
