from .connection import ChannelStatus, MConnection
from .secret_connection import SecretConnection

__all__ = ["SecretConnection", "MConnection", "ChannelStatus"]
