"""MConnection: multiplexed, flow-rate-limited message connection.

Parity with reference p2p/conn/connection.go:27-80: byte-ID channels
with send priorities, 1KB packets with EOF reassembly, token-bucket
send/recv rate limiting (default 500 KB/s like the reference), a 10ms
flush throttle, and ping/pong keepalive with a pong timeout. Runs over
a SecretConnection (one packet == one sealed frame).
"""

from __future__ import annotations

import asyncio
import struct
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...obs.queues import InstrumentedQueue
from .secret_connection import DATA_MAX_SIZE, SecretConnection

PACKET_PING = 0x01
PACKET_PONG = 0x02
PACKET_MSG = 0x03

FLAG_EOF = 0x01

PACKET_HEADER_SIZE = 5  # type + channel + flags + len(2)
PACKET_PAYLOAD_MAX = DATA_MAX_SIZE - PACKET_HEADER_SIZE

DEFAULT_SEND_RATE = 512_000  # bytes/s (reference: 500 KB/s)
DEFAULT_RECV_RATE = 512_000
DEFAULT_FLUSH_THROTTLE_S = 0.010
DEFAULT_PING_INTERVAL_S = 30.0
DEFAULT_PONG_TIMEOUT_S = 45.0
DEFAULT_SEND_QUEUE_CAPACITY = 1000
DEFAULT_MAX_MSG_SIZE = 10 * 1024 * 1024


class FlowRate:
    """Token-bucket byte-rate limiter (reference libs/flowrate)."""

    def __init__(self, rate: int, burst: Optional[int] = None):
        self.rate = rate
        self.burst = burst if burst is not None else rate
        self.tokens = float(self.burst)
        self.last = time.monotonic()
        self.total = 0

    async def throttle(self, n: int) -> None:
        self.total += n
        while True:
            now = time.monotonic()
            self.tokens = min(
                self.burst, self.tokens + (now - self.last) * self.rate
            )
            self.last = now
            if self.tokens >= n:
                self.tokens -= n
                return
            await asyncio.sleep((n - self.tokens) / self.rate)


@dataclass
class ChannelState:
    chan_id: int
    priority: int = 1
    max_msg_size: int = DEFAULT_MAX_MSG_SIZE
    queue: InstrumentedQueue = field(
        default_factory=lambda: InstrumentedQueue(
            DEFAULT_SEND_QUEUE_CAPACITY, name="p2p.send"
        )
    )
    sending: bytes = b""  # remainder of the message currently chunking
    recv_buf: bytearray = field(default_factory=bytearray)
    recently_sent: int = 0  # EWMA'd bytes, for priority fairness


@dataclass
class ChannelStatus:
    chan_id: int
    send_queue_size: int
    priority: int


class MConnection:
    """on_receive(chan_id, msg_bytes) is called for each complete
    message; on_error(exc) once when the connection dies."""

    def __init__(
        self,
        sconn: SecretConnection,
        channels: List[tuple],  # (chan_id, priority[, max_msg_size])
        on_receive: Callable,
        on_error: Optional[Callable] = None,
        send_rate: int = DEFAULT_SEND_RATE,
        recv_rate: int = DEFAULT_RECV_RATE,
        flush_throttle_s: float = DEFAULT_FLUSH_THROTTLE_S,
        ping_interval_s: float = DEFAULT_PING_INTERVAL_S,
        pong_timeout_s: float = DEFAULT_PONG_TIMEOUT_S,
    ):
        self.sconn = sconn
        self.channels: Dict[int, ChannelState] = {}
        for desc in channels:
            cid, prio = desc[0], desc[1]
            cs = ChannelState(cid, prio)
            cs.queue.name = f"p2p.send.{cid:#04x}"
            if len(desc) > 2:
                cs.max_msg_size = desc[2]
            self.channels[cid] = cs
        self.on_receive = on_receive
        self.on_error = on_error
        self.send_flow = FlowRate(send_rate)
        self.recv_flow = FlowRate(recv_rate)
        self.flush_throttle_s = flush_throttle_s
        self.ping_interval_s = ping_interval_s
        self.pong_timeout_s = pong_timeout_s
        self._send_wake = asyncio.Event()
        self._pong_pending = asyncio.Event()
        self._last_recv = time.monotonic()
        self._tasks: List[asyncio.Task] = []
        self._closed = False

    # --- lifecycle ----------------------------------------------------

    def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._send_routine()),
            asyncio.create_task(self._recv_routine()),
            asyncio.create_task(self._ping_routine()),
        ]

    async def stop(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                # bounded (ASY110): a routine that swallows its cancel
                # must not wedge the teardown — the fd close below
                # tears its I/O down regardless
                await asyncio.wait_for(t, 2.0)
            except asyncio.TimeoutError:
                pass
            except asyncio.CancelledError:
                if not t.cancelled():
                    raise  # outer cancel of stop() itself: propagate
            except Exception:
                pass  # routine already reported via _die
        self.sconn.close()

    def abort(self) -> None:
        """Synchronous last-resort close (ShutdownGuard escalation,
        obs/shutdown.py): cancel the routines and close the fd WITHOUT
        awaiting anything. An abandoned graceful stop must still kill
        the socket — a conn left open past shutdown is a zombie the
        remote keeps treating as a live peer (it then dup-discards the
        restarted node's fresh dials and the node can never rejoin)."""
        self._closed = True
        for t in self._tasks:
            t.cancel()
        try:
            self.sconn.close()
        except Exception:
            pass

    def inject_error(self, exc: Exception) -> None:
        """Fault-injection hook (chaos ``reconnect_storm`` /
        ``conn_kill``): kill the connection exactly the way an
        internal routine failure does — e.g. a pong timeout
        (``_ping_routine``) — driving the owner's on_error path and,
        for persistent peers, the self-healing reconnect plane. The
        remote side observes the close as a read error, so BOTH ends
        exercise their conn-death handling."""
        self._die(exc)

    def _die(self, exc: Exception) -> None:
        if self._closed:
            return
        self._closed = True
        for t in self._tasks:
            if t is not asyncio.current_task():
                t.cancel()
        self.sconn.close()
        if self.on_error:
            try:
                self.on_error(exc)
            except Exception:
                traceback.print_exc()

    # --- sending ------------------------------------------------------

    async def send(self, chan_id: int, msg: bytes) -> bool:
        """Queue a message; blocks if the channel queue is full."""
        ch = self.channels.get(chan_id)
        if ch is None or self._closed:
            return False
        await ch.queue.put(bytes(msg))
        self._send_wake.set()
        return True

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        """Queue a message without blocking; False if full/unknown."""
        ch = self.channels.get(chan_id)
        if ch is None or self._closed:
            return False
        try:
            ch.queue.put_nowait(bytes(msg))
        except asyncio.QueueFull:
            ch.queue.count_drop()  # shed under overload, counted
            return False
        self._send_wake.set()
        return True

    def _next_packet(self) -> Optional[bytes]:
        """Pick the channel with the least recently-sent bytes per unit
        priority (reference sendPacketMsg) and cut one packet."""
        best: Optional[ChannelState] = None
        best_score = None
        for ch in self.channels.values():
            if not ch.sending and ch.queue.empty():
                continue
            score = ch.recently_sent / max(ch.priority, 1)
            if best is None or score < best_score:
                best, best_score = ch, score
        if best is None:
            return None
        if not best.sending:
            best.sending = best.queue.get_nowait()
        chunk = best.sending[:PACKET_PAYLOAD_MAX]
        best.sending = best.sending[PACKET_PAYLOAD_MAX:]
        eof = FLAG_EOF if not best.sending else 0
        pkt = (
            struct.pack(
                ">BBBH", PACKET_MSG, best.chan_id, eof, len(chunk)
            )
            + chunk
        )
        best.recently_sent += len(pkt)
        return pkt

    async def _send_routine(self) -> None:
        try:
            while not self._closed:
                pkt = self._next_packet()
                if pkt is None:
                    # decay fairness counters while idle
                    for ch in self.channels.values():
                        ch.recently_sent = int(ch.recently_sent * 0.8)
                    try:
                        await asyncio.wait_for(
                            self._send_wake.wait(), self.flush_throttle_s * 10
                        )
                    except asyncio.TimeoutError:
                        continue
                    self._send_wake.clear()
                    continue
                n = await self.sconn.write_msg(pkt)
                await self.send_flow.throttle(n)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._die(e)

    # --- receiving ----------------------------------------------------

    async def _recv_routine(self) -> None:
        try:
            while not self._closed:
                chunk = await self.sconn.read_chunk()
                self._last_recv = time.monotonic()
                await self.recv_flow.throttle(len(chunk) + 16)
                if not chunk:
                    continue
                ptype = chunk[0]
                if ptype == PACKET_PING:
                    await self.sconn.write_msg(bytes([PACKET_PONG]))
                elif ptype == PACKET_PONG:
                    self._pong_pending.set()
                elif ptype == PACKET_MSG:
                    self._handle_msg_packet(chunk)
                else:
                    raise ValueError(f"unknown packet type {ptype}")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._die(e)

    def _handle_msg_packet(self, pkt: bytes) -> None:
        _, cid, flags, ln = struct.unpack(">BBBH", pkt[:PACKET_HEADER_SIZE])
        data = pkt[PACKET_HEADER_SIZE : PACKET_HEADER_SIZE + ln]
        ch = self.channels.get(cid)
        if ch is None:
            raise ValueError(f"packet for unknown channel {cid:#x}")
        ch.recv_buf.extend(data)
        if len(ch.recv_buf) > ch.max_msg_size:
            raise ValueError(
                f"message on channel {cid:#x} exceeds {ch.max_msg_size}"
            )
        if flags & FLAG_EOF:
            msg = bytes(ch.recv_buf)
            ch.recv_buf.clear()
            self.on_receive(cid, msg)

    # --- keepalive ----------------------------------------------------

    async def _ping_routine(self) -> None:
        try:
            while not self._closed:
                await asyncio.sleep(self.ping_interval_s)
                self._pong_pending.clear()
                await self.sconn.write_msg(bytes([PACKET_PING]))
                try:
                    await asyncio.wait_for(
                        self._pong_pending.wait(), self.pong_timeout_s
                    )
                except asyncio.TimeoutError:
                    raise ConnectionError("pong timeout")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._die(e)

    # --- introspection ------------------------------------------------

    def status(self) -> List[ChannelStatus]:
        return [
            ChannelStatus(c.chan_id, c.queue.qsize(), c.priority)
            for c in self.channels.values()
        ]

    def send_queue_stats(self) -> dict:
        """Aggregate backpressure telemetry over every channel's send
        queue (obs/queues.py semantics: depth summed, watermark is
        the worst single channel, drops summed)."""
        depth = hwm = dropped = enqueued = 0
        for ch in self.channels.values():
            q = ch.queue
            depth += q.qsize()
            hwm = max(hwm, q.high_watermark)
            dropped += q.dropped
            enqueued += q.enqueued
        # aggregate entry: no "maxsize" (summed depth must not be
        # compared against the per-channel bound by health's
        # full-queue check)
        return {
            "depth": depth,
            "high_watermark": hwm,
            "dropped": dropped,
            "enqueued": enqueued,
            "per_channel_maxsize": DEFAULT_SEND_QUEUE_CAPACITY,
        }
