"""Authenticated encrypted duplex connection (STS protocol).

Parity with reference p2p/conn/secret_connection.go:33-60,129-152,349:
ephemeral X25519 ECDH -> transcript hash -> HKDF-SHA256 key schedule ->
ChaCha20-Poly1305 AEAD over fixed 1024-byte frames, then each side
proves its long-lived ed25519 identity by signing the handshake
challenge INSIDE the encrypted channel (so eavesdroppers never link
node identity to address). Wire format is framework-native, not
byte-compatible with the reference (merlin transcripts are replaced by
a plain SHA-256 transcript chain).

Frames: plaintext = 2-byte BE length || data, zero-padded to
DATA_MAX_SIZE+2; ciphertext = frame || 16-byte tag. Nonce = 12-byte
little-endian per-direction send counter (independent keys per
direction, so counters never collide).
"""

from __future__ import annotations

import asyncio
import hashlib
import struct
from typing import Optional, Tuple

# both primitives are dependency-gated: OpenSSL when the
# `cryptography` package exists, pure-Python/numpy fallback otherwise
from ...crypto import x25519 as _x25519
from ...crypto.chacha20poly1305 import ChaCha20Poly1305
from ...crypto.keys import Ed25519PrivKey, Ed25519PubKey

DATA_LEN_SIZE = 2
DATA_MAX_SIZE = 1022
FRAME_SIZE = DATA_LEN_SIZE + DATA_MAX_SIZE  # 1024
SEALED_FRAME_SIZE = FRAME_SIZE + 16
TRANSCRIPT_DOMAIN = b"COMETBFT_TPU_SECRET_CONNECTION_V1"


class HandshakeError(Exception):
    pass


def _kdf(shared: bytes, transcript: bytes) -> Tuple[bytes, bytes, bytes]:
    """96 bytes of key material: (key_lo, key_hi, challenge)."""
    okm = b""
    prk = hashlib.sha256(transcript + shared).digest()
    t = b""
    for i in range(3):
        t = hashlib.sha256(prk + t + bytes([i + 1])).digest()
        okm += t
    return okm[0:32], okm[32:64], okm[64:96]


class SecretConnection:
    """Wraps an (asyncio) byte stream after a successful handshake."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_key: bytes,
        recv_key: bytes,
        remote_pubkey: Ed25519PubKey,
    ):
        self._reader = reader
        self._writer = writer
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_nonce = 0
        self._recv_nonce = 0
        self.remote_pubkey = remote_pubkey
        self._recv_buf = b""
        self._write_lock = asyncio.Lock()
        self._read_lock = asyncio.Lock()

    # --- handshake ----------------------------------------------------

    @classmethod
    async def handshake(
        cls,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        priv_key: Ed25519PrivKey,
        timeout: float = 10.0,
    ) -> "SecretConnection":
        return await asyncio.wait_for(
            cls._handshake(reader, writer, priv_key), timeout
        )

    @classmethod
    async def _handshake(cls, reader, writer, priv_key):
        eph_priv = _x25519.generate_private()
        eph_pub = _x25519.public(eph_priv)
        writer.write(eph_pub)
        await writer.drain()
        their_eph = await reader.readexactly(32)
        if their_eph == eph_pub:
            raise HandshakeError("reflected ephemeral key (self-connection?)")

        lo, hi = sorted((eph_pub, their_eph))
        transcript = hashlib.sha256(
            TRANSCRIPT_DOMAIN + lo + hi
        ).digest()
        shared = _x25519.shared(eph_priv, their_eph)
        key_lo, key_hi, challenge = _kdf(shared, transcript)
        # the party whose ephemeral key sorts lower sends with key_lo
        if eph_pub == lo:
            send_key, recv_key = key_lo, key_hi
        else:
            send_key, recv_key = key_hi, key_lo

        conn = cls.__new__(cls)
        SecretConnection.__init__(
            conn, reader, writer, send_key, recv_key, None
        )

        # authenticate inside the encrypted channel: pubkey || sig(challenge)
        my_pub = bytes(priv_key.pub_key().key_bytes)
        sig = priv_key.sign(challenge)
        await conn.write_msg(my_pub + sig)
        auth = await conn.read_msg()
        if len(auth) != 32 + 64:
            raise HandshakeError("bad auth message length")
        remote_pub = Ed25519PubKey(auth[:32])
        if not remote_pub.verify(challenge, auth[32:]):
            raise HandshakeError("challenge signature verification failed")
        conn.remote_pubkey = remote_pub
        return conn

    # --- framed AEAD I/O ----------------------------------------------

    def _seal(self, data: bytes) -> bytes:
        frame = struct.pack(">H", len(data)) + data
        frame += b"\x00" * (FRAME_SIZE - len(frame))
        nonce = self._send_nonce.to_bytes(12, "little")
        self._send_nonce += 1
        return self._send_aead.encrypt(nonce, frame, None)

    def _open(self, sealed: bytes) -> bytes:
        nonce = self._recv_nonce.to_bytes(12, "little")
        self._recv_nonce += 1
        frame = self._recv_aead.decrypt(nonce, sealed, None)
        (n,) = struct.unpack(">H", frame[:DATA_LEN_SIZE])
        if n > DATA_MAX_SIZE:
            raise HandshakeError("corrupt frame length")
        return frame[DATA_LEN_SIZE : DATA_LEN_SIZE + n]

    async def write_msg(self, data: bytes) -> int:
        """Write data as one or more sealed frames. Returns bytes sent
        on the wire."""
        sent = 0
        async with self._write_lock:
            for i in range(0, len(data) or 1, DATA_MAX_SIZE):
                chunk = data[i : i + DATA_MAX_SIZE]
                sealed = self._seal(chunk)
                self._writer.write(sealed)
                sent += len(sealed)
            await self._writer.drain()
        return sent

    async def read_chunk(self) -> bytes:
        """Read exactly one frame's payload (<= DATA_MAX_SIZE bytes)."""
        async with self._read_lock:
            sealed = await self._reader.readexactly(SEALED_FRAME_SIZE)
        return self._open(sealed)

    async def read_msg(self) -> bytes:
        """Read one frame payload (handshake helper; MConnection does
        its own message reassembly from chunks)."""
        return await self.read_chunk()

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass
