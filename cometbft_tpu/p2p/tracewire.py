"""Cross-node trace-context stamping for p2p messages (docs/TRACE.md
"Cross-node timelines").

Consensus, mempool-gossip and blocksync messages can ride the wire
with a compact causal context — origin node, message kind, height /
round, origin send timestamp (monotonic ns), origin clock-domain id
and a per-origin sequence number — so each receiver can record a
correlated receive instant in its own trace ring and the offline
timeline tool (trace/timeline.py) can stitch every node's ring into
one causally-ordered view.

Wire form mirrors the PR 5 mempool gossip codec (mempool/codec.py):
the stamp is an OPTIONAL magic-prefixed header in front of the
otherwise-unchanged reactor message:

    MAGIC(2) | uvarint(hdr_len >= 1) | hdr | payload      stamped
    MAGIC(2) | 0x00                  | payload            escape
    payload                                               unstamped

    hdr = uvarint(kind_id) | uvarint(seq) | uvarint(send_ns)
        | uvarint(clock) | uvarint(height) | uvarint(round + 1)
        | uvarint(len(origin)) | origin-utf8

Compatibility contract, both directions (tests/test_tracewire.py):

- ``unstamp`` treats anything not starting with MAGIC as a raw
  unstamped message, and falls back to raw on ANY parse failure after
  the magic — an old peer relaying a message that happens to begin
  with the magic bytes still decodes losslessly.
- a stamping-disabled sender that must emit a payload beginning with
  MAGIC escapes it as a zero-length header frame, so a new receiver
  can always tell the two apart; ``unstamp(stamp(m)) == m`` and
  ``unstamp(escape(m)) == m`` for every payload.

Timestamps here are ``time.monotonic_ns`` of the ORIGIN — meaningful
to a receiver only inside the same clock domain (one process).
``clock`` carries a random per-process domain id so receivers compute
live propagation only when the clocks actually compare; cross-process
correlation instead goes through each ring's monotonic→wall anchor
(recorded at tracer build, node/inprocess.py) in the timeline tool.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Optional, Tuple

_monotonic_ns = time.monotonic_ns

# 0xB7 echoes the mempool codec's non-ASCII lead byte; 0x54 = "T"
MAGIC = b"\xb7\x54"

# message kinds a stamp may carry (wire ids are positional — append
# only; an unknown id on decode falls back to raw, like any parse
# failure, so old receivers never misread new kinds)
KINDS = (
    "proposal",
    "block_part",
    "vote",
    "commit_block",
    "txs",
    "bs.status",
    "bs.request",
    "bs.block",
)
_KIND_ID = {k: i for i, k in enumerate(KINDS)}

# per-process clock-domain id (nonzero): receivers compute live
# propagation deltas only when the sender's domain matches their own
CLOCK_DOMAIN = int.from_bytes(os.urandom(4), "big") | 1

# worst-case stamp size (magic + len + full header with a long
# origin): senders near a channel's max_msg_size skip the stamp
# rather than cross the cap (same guard as the mempool batch escape)
STAMP_MAX_OVERHEAD = 64

_MAX_ORIGIN_LEN = 32


class TraceCtx:
    """Decoded stamp: who sent this message, about what, and when
    (origin monotonic ns)."""

    __slots__ = ("kind", "seq", "send_ns", "clock", "height", "round",
                 "origin")

    def __init__(self, kind, seq, send_ns, clock, height, round_, origin):
        self.kind = kind
        self.seq = seq
        self.send_ns = send_ns
        self.clock = clock
        self.height = height
        self.round = round_
        self.origin = origin

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"TraceCtx({self.kind} h={self.height} r={self.round} "
            f"seq={self.seq} from={self.origin})"
        )


def _put_uvarint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _read_uvarint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if pos >= len(buf) or shift > 63:
            raise ValueError("truncated/overlong varint")
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def stamp(
    payload: bytes,
    kind: str,
    seq: int,
    origin: str,
    height: int = 0,
    round_: int = -1,
    send_ns: Optional[int] = None,
    clock: int = CLOCK_DOMAIN,
) -> bytes:
    """Prefix ``payload`` with a trace-context header."""
    hdr = bytearray()
    _put_uvarint(hdr, _KIND_ID[kind])
    _put_uvarint(hdr, seq)
    _put_uvarint(hdr, send_ns if send_ns is not None else _monotonic_ns())
    _put_uvarint(hdr, clock)
    _put_uvarint(hdr, max(0, height))
    _put_uvarint(hdr, round_ + 1)  # -1 (no round) encodes as 0
    o = origin.encode()[:_MAX_ORIGIN_LEN]
    _put_uvarint(hdr, len(o))
    hdr += o
    out = bytearray(MAGIC)
    _put_uvarint(out, len(hdr))
    out += hdr
    out += payload
    return bytes(out)


def escape(payload: bytes) -> bytes:
    """Zero-header frame: a stamping-disabled sender whose payload
    happens to begin with MAGIC wraps it so the receiver cannot
    misparse it as a stamp."""
    return MAGIC + b"\x00" + payload


def encode_plain(payload: bytes, cap: int = 0) -> bytes:
    """Wire form for an unstamped send: raw bytes, escaping only the
    (vanishingly rare) MAGIC-prefixed payload so the receiver's
    always-on peel cannot mutate it. ``cap`` is the channel's max
    message size: a magic-prefixed payload within 3 bytes of the cap
    goes out raw rather than oversized — the one remaining aliasing
    window (cap-sized AND magic-prefixed AND header-parseable) is
    vanishingly small, the same compromise the mempool batch codec
    makes for its own oversize escape."""
    if payload.startswith(MAGIC) and (
        not cap or len(payload) + len(MAGIC) + 1 <= cap
    ):
        return escape(payload)
    return payload


def unstamp(msg: bytes) -> Tuple[Optional[TraceCtx], bytes]:
    """(ctx-or-None, payload). Anything unparseable — including an
    old peer's raw message that happens to begin with MAGIC — comes
    back as (None, msg) unchanged."""
    if not msg.startswith(MAGIC):
        return None, msg
    try:
        hdr_len, pos = _read_uvarint(msg, len(MAGIC))
        if hdr_len == 0:
            return None, msg[pos:]  # escape frame
        end = pos + hdr_len
        if end > len(msg):
            raise ValueError("truncated header")
        kind_id, pos = _read_uvarint(msg, pos)
        if kind_id >= len(KINDS):
            raise ValueError("unknown kind id")
        seq, pos = _read_uvarint(msg, pos)
        send_ns, pos = _read_uvarint(msg, pos)
        clock, pos = _read_uvarint(msg, pos)
        height, pos = _read_uvarint(msg, pos)
        round1, pos = _read_uvarint(msg, pos)
        olen, pos = _read_uvarint(msg, pos)
        if pos + olen != end or olen > _MAX_ORIGIN_LEN:
            raise ValueError("bad origin length")
        origin = msg[pos:end].decode()
        return (
            TraceCtx(
                KINDS[kind_id], seq, send_ns, clock, height,
                round1 - 1, origin,
            ),
            msg[end:],
        )
    except (ValueError, UnicodeDecodeError):
        # old peer relaying raw bytes that start with our magic
        return None, msg


class TraceStamper:
    """Per-switch stamping plane: wraps outbound messages with a
    trace context and records correlated send/recv instants in the
    node's ring (docs/TRACE.md "Cross-node timelines").

    Built by the node wiring whenever the tracer is enabled;
    ``Switch`` holds ``stamper = None`` otherwise, so the fully-off
    path is one attribute check per send and a startswith per
    receive. ``outbound`` mirrors ``[instrumentation]
    trace_msg_stamp``: False stops this node stamping its own sends
    while receive-side correlation (``on_receive``) keeps recording
    arrivals from stamping peers — decode is always on.
    """

    __slots__ = ("tracer", "origin", "outbound", "_seq")

    def __init__(self, tracer, origin: str, outbound: bool = True):
        self.tracer = tracer
        self.origin = origin
        self.outbound = outbound
        # per-origin sequence: the recv-side correlation key
        self._seq = itertools.count()

    def wrap(
        self,
        payload: bytes,
        kind: str,
        height: int = 0,
        round_: int = -1,
        cap: int = 0,
        peer: str = "",
        npeers: int = 0,
    ) -> bytes:
        """Stamp + record a ``p2p.msg.send`` instant. ``cap`` is the
        channel's max message size: a payload too close to it goes out
        unstamped (escaped if magic-prefixed) rather than oversized."""
        if cap and len(payload) + STAMP_MAX_OVERHEAD > cap:
            return encode_plain(payload, cap)
        seq = next(self._seq)
        send_ns = _monotonic_ns()
        wire = stamp(
            payload, kind, seq, self.origin,
            height=height, round_=round_, send_ns=send_ns,
        )
        args = {"kind": kind, "h": height, "r": round_, "seq": seq}
        if peer:
            args["peer"] = peer
        if npeers:
            args["n"] = npeers
        self.tracer.instant_at("p2p.msg.send", send_ns, tid="p2p", **args)
        return wire

    def on_receive(self, ctx: TraceCtx, peer_id: str) -> None:
        """Record the correlated receive instant (+ a live propagation
        span when the sender shares our clock domain)."""
        tr = self.tracer
        if not tr.enabled:
            return
        recv_ns = _monotonic_ns()
        tr.instant_at(
            "p2p.msg.recv", recv_ns, tid="p2p",
            kind=ctx.kind, h=ctx.height, r=ctx.round, seq=ctx.seq,
            origin=ctx.origin, send_ns=ctx.send_ns, peer=peer_id[:12],
        )
        if ctx.clock == CLOCK_DOMAIN:
            dur = recv_ns - ctx.send_ns
            if dur >= 0:
                tr.complete(
                    "p2p.msg.propagation", ctx.send_ns, dur, tid="p2p",
                    kind=ctx.kind, origin=ctx.origin, h=ctx.height,
                )
