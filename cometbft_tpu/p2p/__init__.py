"""P2P layer: authenticated-encrypted TCP transport, multiplexed
channels, peer lifecycle, switch + reactor plumbing.

Parity map (reference -> here):
- p2p/key.go              -> key.py (NodeKey, ID derivation)
- p2p/conn/secret_connection.go -> conn/secret_connection.py
- p2p/conn/connection.go  -> conn/connection.py (MConnection)
- p2p/transport.go        -> transport.py (TCP + in-memory)
- p2p/peer.go             -> peer.py
- p2p/switch.go           -> switch.py (+ reconnect.py: the
  self-healing never-give-up redial plane, fork addition)
- p2p/base_reactor.go     -> reactor.py
- p2p/pex/                -> pex.py (addrbook + PEX reactor)
"""

from .key import NodeKey, node_id_from_pubkey
from .node_info import ChannelDescriptor, NodeInfo
from .peer import Peer
from .reactor import Reactor
from .reconnect import ReconnectPlane
from .switch import Switch
from .transport import MemoryTransport, TCPTransport

__all__ = [
    "NodeKey",
    "node_id_from_pubkey",
    "NodeInfo",
    "ChannelDescriptor",
    "Peer",
    "Reactor",
    "ReconnectPlane",
    "Switch",
    "TCPTransport",
    "MemoryTransport",
]
