"""Self-healing reconnect plane: never-give-up budgeted redial.

The reference (and the seed tree before this plane) abandoned a
persistent peer after a finite attempt budget
(``for _ in range(MAX_RECONNECT_ATTEMPTS)`` — the exact shape bftlint
ASY112 now flags): pong-timeout conn deaths during a partition plus
one-sided reconnect exhaustion left a healed minority PERMANENTLY
isolated, which is a liveness violation the BFT fault model does not
tolerate (the chaos matrix found it; PAPERS.md "A Tendermint Light
Client" formalizes the assumption we broke).

This plane replaces the give-up with two lanes that together never
abandon a persistent peer:

- **fast lane** — per-peer task: full-jitter exponential backoff
  (``utils/backoff.py``, the one shared policy) up to a per-peer
  attempt *budget*. A healed network converges at backoff speed.
- **slow lane** — after the fast budget is spent the peer is PARKED,
  not dropped: one periodic sweep redials every parked peer forever.
  The lane bounds steady-state dial load to
  ``len(slow_lane) / slow_interval_s`` regardless of how long the
  outage lasts.

Any successful handshake resets the peer's backoff (the next flap
starts fast again) and un-parks it. Address resolution consults the
PEX address book FIRST — a peer that moved (restarted elsewhere,
readvertised via PEX) is redialed at its re-learned address, not the
static ``persistent_addrs`` snapshot taken at boot.

Starvation: a node with ZERO peers for ``starvation_s`` is starving —
the switch then broadcasts PEX requests on every dial success so a
healed minority re-learns moved addresses immediately instead of
waiting out the crawl interval. Cumulative zero-peer time is exported
as ``cometbft_p2p_starvation_seconds``.

Observability: every death→re-establish cycle is one
``p2p.reconnect`` span (args: attempts, lane) gated by
``tools/span_budgets.toml``; attempt/flap counters ride the trace
counter stream and the PR 4 metrics bridge.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Set

from ..utils.backoff import Backoff
from ..utils.log import get_logger

_log = get_logger("p2p.reconnect")

DEFAULT_BASE_S = 1.0
DEFAULT_CAP_S = 30.0
# fast-lane dial budget per outage, NOT a give-up bound: spending it
# hands the peer to the slow lane (ASY112)
DEFAULT_FAST_ATTEMPTS = 12
DEFAULT_SLOW_INTERVAL_S = 30.0
DEFAULT_STARVATION_S = 10.0


class ReconnectPlane:
    """Owns persistent-peer redial for a Switch (both flavors: the
    native Switch and Lp2pSwitch share one instance by inheritance).
    All entry points are loop-synchronous; only the lane routines
    await."""

    def __init__(
        self,
        switch,
        base_s: float = DEFAULT_BASE_S,
        cap_s: float = DEFAULT_CAP_S,
        fast_attempts: int = DEFAULT_FAST_ATTEMPTS,
        slow_interval_s: float = DEFAULT_SLOW_INTERVAL_S,
        starvation_s: float = DEFAULT_STARVATION_S,
    ):
        self.switch = switch
        self.base_s = base_s
        self.cap_s = max(cap_s, base_s)
        self.fast_attempts = max(1, int(fast_attempts))
        self.slow_interval_s = slow_interval_s
        self.starvation_s = starvation_s
        self._backoffs: Dict[str, Backoff] = {}
        self._fast_tasks: Dict[str, asyncio.Task] = {}
        self.slow_lane: Set[str] = set()
        self._spans: Dict[str, object] = {}  # open p2p.reconnect spans
        self._attempts_this_outage: Dict[str, int] = {}
        self._sweep_task: Optional[asyncio.Task] = None
        self._stopped = False
        # counters (RPC health `connectivity` + the metrics bridge)
        self.attempts_total = 0
        self.dial_failures_total = 0
        self.flaps_total = 0
        self.slow_parks_total = 0
        self.recoveries_total = 0
        # zero-peer clock: episodes accumulate into starvation_total_s;
        # the running episode is added by starvation_seconds()
        self._zero_since: Optional[float] = time.monotonic()
        self.starvation_total_s = 0.0

    # --- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._sweep_task is None:
            self._sweep_task = asyncio.create_task(self._sweep_routine())

    def stop(self) -> None:
        """Synchronous cancel of every lane task (safe from both the
        graceful stop chain and the abort floor — nothing awaits)."""
        self._stopped = True
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            self._sweep_task = None
        for t in self._fast_tasks.values():
            t.cancel()
        self._fast_tasks.clear()
        self.slow_lane.clear()
        self._spans.clear()

    # --- switch hooks -------------------------------------------------

    def on_peer_connected(self, peer) -> bool:
        """Any successful handshake: reset the peer's backoff, un-park
        it, close its reconnect span. Returns True when the node was
        STARVING until this connection (the switch then triggers the
        PEX re-learn storm)."""
        pid = peer.peer_id
        bo = self._backoffs.get(pid)
        if bo is not None:
            bo.reset()
        was_scheduled = pid in self.slow_lane or pid in self._fast_tasks
        self.slow_lane.discard(pid)
        t = self._fast_tasks.get(pid)
        if t is not None and t is not asyncio.current_task():
            t.cancel()
            self._fast_tasks.pop(pid, None)
        span = self._spans.pop(pid, None)
        if span is not None:
            span.set(
                attempts=self._attempts_this_outage.pop(pid, 0),
                recovered=True,
            )
            span.end()
            self.recoveries_total += 1
        elif was_scheduled:
            self.recoveries_total += 1
        was_starving = self.starving()
        if self._zero_since is not None:
            self.starvation_total_s += self.zero_peers_for_s()
            self._zero_since = None
        return was_starving

    def _book_addr(self, peer_id: str) -> str:
        """Book-form ("id@addr") of what we would dial, so failure
        bookkeeping can CREATE the entry for a persistent peer that
        was never PEX-learned (otherwise its history silently no-ops
        against an absent entry)."""
        addr = self.switch.persistent_addrs.get(peer_id)
        if not addr:
            return ""
        return addr if "@" in addr else f"{peer_id}@{addr}"

    def on_peer_removed(self, peer, had_error: bool) -> None:
        """Conn death. On error paths: counts the flap, records the
        failure in the address book, and (for persistent peers)
        schedules the fast lane. Graceful hang-ups (seed-mode serve,
        operator drop) roll only the zero-peer clock."""
        pid = peer.peer_id
        sw = self.switch
        if had_error:
            self.flaps_total += 1
            sw.tracer.counter(
                "p2p.peer_flaps", self.flaps_total, tid="p2p"
            )
            book = getattr(sw, "addr_book", None)
            if book is not None:
                book.mark_failed(pid, self._book_addr(pid))
        if sw.num_peers() == 0 and self._zero_since is None:
            self._zero_since = time.monotonic()
        if had_error and peer.persistent and not self._stopped:
            self.schedule(pid)

    def note_dial_failure(self, peer_id: str) -> None:
        """An explicitly-requested persistent dial failed before any
        peer existed (boot dial against a partitioned/crashed target):
        the plane owns the retry from here."""
        self.dial_failures_total += 1
        book = getattr(self.switch, "addr_book", None)
        if book is not None:
            book.mark_failed(peer_id, self._book_addr(peer_id))
        self.schedule(peer_id)

    # --- scheduling ---------------------------------------------------

    def is_scheduled(self, peer_id: str) -> bool:
        return peer_id in self._fast_tasks or peer_id in self.slow_lane

    def schedule(self, peer_id: str) -> None:
        """Idempotent entry: start the fast lane for a dead persistent
        peer (no-op while either lane already owns it)."""
        if self._stopped or self.is_scheduled(peer_id):
            return
        if peer_id in self.switch.peers or peer_id in self.switch.banned:
            return
        if not self.resolve_addr(peer_id):
            return
        if peer_id not in self._spans:
            self._spans[peer_id] = self.switch.tracer.span(
                "p2p.reconnect", tid="p2p", peer=peer_id[:12]
            )
            self._attempts_this_outage[peer_id] = 0
        self._fast_tasks[peer_id] = asyncio.create_task(
            self._fast_routine(peer_id)
        )

    def resolve_addr(self, peer_id: str) -> Optional[str]:
        """Current best address: the PEX book's live entry beats the
        boot-time persistent snapshot (nodes move; PEX re-learns)."""
        sw = self.switch
        book = getattr(sw, "addr_book", None)
        if book is not None:
            ka = book.addrs.get(peer_id)
            if ka is not None and ka.addr:
                return ka.addr
        return sw.persistent_addrs.get(peer_id)

    # --- lanes --------------------------------------------------------

    def _backoff_for(self, peer_id: str) -> Backoff:
        bo = self._backoffs.get(peer_id)
        if bo is None:
            bo = self._backoffs[peer_id] = Backoff(
                base_s=self.base_s, cap_s=self.cap_s
            )
        return bo

    def abandon(self, peer_id: str) -> None:
        """The ONE sanctioned abandonment: the peer got banned — drop
        it from every lane (its open span is discarded unrecorded)."""
        self.slow_lane.discard(peer_id)
        t = self._fast_tasks.pop(peer_id, None)
        if t is not None and t is not asyncio.current_task():
            t.cancel()
        self._spans.pop(peer_id, None)
        self._attempts_this_outage.pop(peer_id, None)

    async def _try_dial(self, peer_id: str, lane: str) -> bool:
        if peer_id in self.switch.banned:
            self.abandon(peer_id)
            return True  # stop retrying; NOT a recovery (span dropped)
        addr = self.resolve_addr(peer_id)
        if addr is None:
            return False
        sw = self.switch
        self.attempts_total += 1
        if peer_id in self._attempts_this_outage:
            self._attempts_this_outage[peer_id] += 1
        sw.tracer.counter(
            "p2p.reconnect.attempts", self.attempts_total, tid="p2p"
        )
        book = getattr(sw, "addr_book", None)
        if book is not None:
            book.mark_attempt(peer_id)
        try:
            await sw.dial_peer(addr, peer_id)
            return True
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.dial_failures_total += 1
            if book is not None:
                book.mark_failed(peer_id, self._book_addr(peer_id))
            _log.debug(
                "reconnect dial failed",
                peer=peer_id[:12], lane=lane, err=repr(e),
            )
            return False

    async def _fast_routine(self, peer_id: str) -> None:
        try:
            backoff = self._backoff_for(peer_id)
            attempt = 0
            while attempt < self.fast_attempts:
                await asyncio.sleep(backoff.next_delay())
                if self._stopped or peer_id in self.switch.peers:
                    return
                attempt += 1
                if await self._try_dial(peer_id, lane="fast"):
                    return
            # fast budget spent: the peer is PARKED for the periodic
            # sweep, never abandoned (the ASY112 contract)
            self._park_slow_lane(peer_id)
        except asyncio.CancelledError:
            raise
        finally:
            self._fast_tasks.pop(peer_id, None)

    def _park_slow_lane(self, peer_id: str) -> None:
        if self._stopped or peer_id in self.switch.peers:
            return
        self.slow_lane.add(peer_id)
        self.slow_parks_total += 1
        span = self._spans.get(peer_id)
        if span is not None:
            span.set(slow_lane=True)
        _log.info(
            "reconnect fast budget spent, parked in slow lane",
            peer=peer_id[:12], budget=self.fast_attempts,
        )

    async def _sweep_routine(self) -> None:
        try:
            while not self._stopped:
                await asyncio.sleep(self.slow_interval_s)
                for peer_id in sorted(self.slow_lane):
                    if self._stopped:
                        return
                    if peer_id in self.switch.peers:
                        self.slow_lane.discard(peer_id)
                        continue
                    if await self._try_dial(peer_id, lane="slow"):
                        self.slow_lane.discard(peer_id)
        except asyncio.CancelledError:
            raise

    # --- starvation ---------------------------------------------------

    def expects_peers(self) -> bool:
        """Whether zero peers is a PROBLEM: the node has persistent
        peers configured, learned addresses, or has lost peers before.
        A single-node net with nothing to dial is not starving."""
        sw = self.switch
        if sw.persistent_addrs or self.flaps_total:
            return True
        book = getattr(sw, "addr_book", None)
        return book is not None and book.size() > 0

    def zero_peers_for_s(self) -> float:
        if self._zero_since is None or not self.expects_peers():
            return 0.0
        return time.monotonic() - self._zero_since

    def starving(self) -> bool:
        """Zero peers for at least ``starvation_s``."""
        return self.zero_peers_for_s() >= self.starvation_s

    def starvation_seconds(self) -> float:
        """Cumulative zero-peer seconds (completed episodes + the
        running one) — the ``cometbft_p2p_starvation_seconds`` feed."""
        return self.starvation_total_s + self.zero_peers_for_s()

    # --- introspection ------------------------------------------------

    def stats(self) -> dict:
        return {
            "attempts_total": self.attempts_total,
            "dial_failures_total": self.dial_failures_total,
            "flaps_total": self.flaps_total,
            "slow_parks_total": self.slow_parks_total,
            "recoveries_total": self.recoveries_total,
            "fast_lane": len(self._fast_tasks),
            "slow_lane": len(self.slow_lane),
            "starving_for_s": round(self.zero_peers_for_s(), 3),
            "starvation_seconds": round(self.starvation_seconds(), 3),
        }
