"""Peer: a connected remote node (reference p2p/peer.go:23).

Wraps the MConnection with identity/metadata and a small KV store that
reactors use to stash per-peer state (reference peer.Set/Get).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, List, Optional

from .conn.connection import MConnection
from .conn.secret_connection import SecretConnection
from .node_info import NodeInfo


class Peer:
    def __init__(
        self,
        sconn: SecretConnection,
        node_info: NodeInfo,
        conn_str: str,
        channels: List[tuple],
        on_receive: Callable,  # (chan_id, msg_bytes, peer)
        on_error: Optional[Callable] = None,  # (peer, exc)
        outbound: bool = False,
        persistent: bool = False,
        mconn_config: Optional[dict] = None,
    ):
        self.node_info = node_info
        self.conn_str = conn_str
        self.outbound = outbound
        self.persistent = persistent
        self._data: Dict[str, Any] = {}
        self.mconn = MConnection(
            sconn,
            channels,
            on_receive=lambda cid, msg: on_receive(cid, msg, self),
            on_error=(lambda e: on_error(self, e)) if on_error else None,
            **(mconn_config or {}),
        )

    # --- identity -----------------------------------------------------

    @property
    def peer_id(self) -> str:
        return self.node_info.node_id

    def __repr__(self) -> str:
        return f"Peer({self.peer_id[:10]}@{self.conn_str})"

    # --- lifecycle ----------------------------------------------------

    def start(self) -> None:
        self.mconn.start()

    async def stop(self) -> None:
        # bounded (ASY110): mconn.stop is itself bounded, this is the
        # belt over its braces — a hung peer must never hang the switch
        try:
            await asyncio.wait_for(self.mconn.stop(), 7.0)
        except asyncio.TimeoutError:
            # graceful close ran out of budget mid-drain: the fd MUST
            # still die or the remote keeps a zombie peer entry that
            # dup-discards this node's next incarnation (the rejoin
            # wedge, obs/shutdown.py) — abort is sync and total
            self.mconn.abort()

    def abort(self) -> None:
        """Synchronous last-resort close (never awaits): see
        MConnection.abort."""
        self.mconn.abort()

    def inject_error(self, exc: Exception) -> None:
        """Chaos hook: die as if ``exc`` came from a conn routine
        (e.g. an injected pong timeout) — see MConnection.inject_error."""
        self.mconn.inject_error(exc)

    # --- messaging ----------------------------------------------------

    async def send(self, chan_id: int, msg: bytes) -> bool:
        return await self.mconn.send(chan_id, msg)

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        return self.mconn.try_send(chan_id, msg)

    # --- traffic totals (uniform across peer implementations) ---------

    @property
    def recv_total(self) -> int:
        return self.mconn.recv_flow.total

    @property
    def send_total(self) -> int:
        return self.mconn.send_flow.total

    # --- per-peer reactor state ---------------------------------------

    def get(self, key: str, default=None):
        return self._data.get(key, default)

    def set(self, key: str, value) -> None:
        self._data[key] = value
