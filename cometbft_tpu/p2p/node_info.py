"""NodeInfo: what peers advertise during the post-encryption handshake
(reference p2p/node_info.go + node/node.go:1022-1071 makeNodeInfo).

Compatibility rules mirror the reference: same network (chain id),
at least one common channel, and — for outbound dials — the proven
identity (pubkey from the secret connection) must match the dialed ID.
"""

from __future__ import annotations

import json
import secrets
from dataclasses import dataclass, field
from typing import List


@dataclass
class ChannelDescriptor:
    chan_id: int
    priority: int = 1
    max_msg_size: int = 10 * 1024 * 1024


def _new_incarnation() -> str:
    """Per-process handshake nonce: one draw per constructed NodeInfo,
    so every incarnation of a node (each restart builds a fresh
    NodeInfo) advertises a distinct value. The switch's duplicate-conn
    resolution keys on (node id, incarnation) — a restarted remote's
    fresh dial must never be dup-discarded against its previous life's
    zombie entry."""
    return secrets.token_hex(8)


@dataclass
class NodeInfo:
    node_id: str
    network: str  # chain id
    listen_addr: str = ""
    version: str = "0.1.0"
    channels: List[int] = field(default_factory=list)
    moniker: str = ""
    rpc_address: str = ""
    # incarnation-safe dialing (p2p/switch.py _new_conn_wins); ""
    # on DECODED info from a peer that predates the field
    incarnation: str = field(default_factory=_new_incarnation)

    def encode(self) -> bytes:
        return json.dumps(
            {
                "node_id": self.node_id,
                "network": self.network,
                "listen_addr": self.listen_addr,
                "version": self.version,
                "channels": self.channels,
                "moniker": self.moniker,
                "rpc_address": self.rpc_address,
                "incarnation": self.incarnation,
            }
        ).encode()

    @classmethod
    def decode(cls, b: bytes) -> "NodeInfo":
        d = json.loads(b.decode())
        return cls(
            node_id=d["node_id"],
            network=d["network"],
            listen_addr=d.get("listen_addr", ""),
            version=d.get("version", ""),
            channels=list(d.get("channels", [])),
            moniker=d.get("moniker", ""),
            rpc_address=d.get("rpc_address", ""),
            incarnation=d.get("incarnation", ""),
        )

    def compatible_with(self, other: "NodeInfo") -> None:
        if other.network != self.network:
            raise ValueError(
                f"peer is on network {other.network!r}, not {self.network!r}"
            )
        if self.channels and other.channels:
            if not set(self.channels) & set(other.channels):
                raise ValueError("no common channels with peer")
