"""FuzzedConnection: network fault injection without a cluster.

Reference p2p/fuzz.go:14 — wraps a connection and randomly drops,
delays, or kills traffic so reactor/peer code is exercised under
pathological networks in ordinary tests. Wraps our SecretConnection
surface (write_msg/read_chunk) instead of a raw socket: the faults
land between the mux/mconnection layer and the wire, which is where
the reference's net.Conn wrapper sits relative to its stack.

Config: [fuzz] section (reference config/config.go:896
FuzzConnConfig), applied by the transport when enabled.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import Optional

MODE_DROP = "drop"
MODE_DELAY = "delay"


@dataclass
class FuzzConnConfig:
    enable: bool = False
    mode: str = MODE_DROP
    max_delay_ms: int = 3000
    prob_drop_rw: float = 0.2
    prob_drop_conn: float = 0.00
    prob_sleep: float = 0.00
    seed: Optional[int] = None


class FuzzedConnection:
    """Same surface as SecretConnection; every read/write may be
    dropped (write reports success, bytes vanish), delayed, or the
    whole connection torn down, per config probabilities."""

    def __init__(self, sconn, config: FuzzConnConfig, rng=None):
        self._sconn = sconn
        self._cfg = config
        # rng injection: the chaos link plane (chaos/links.LinkTable)
        # composes a per-link seeded stream so fuzz decisions replay
        # deterministically alongside link faults
        self._rng = rng or random.Random(getattr(config, "seed", None))
        self._dead = False

    # counters for tests/metrics
    dropped_writes = 0
    dropped_reads = 0

    def __getattr__(self, name):
        # identity/lifecycle passthrough (local_pubkey, close, ...)
        return getattr(self._sconn, name)

    async def _fuzz(self) -> bool:
        """Apply one fault decision; returns True if the op should be
        swallowed."""
        cfg = self._cfg
        if self._dead:
            raise ConnectionError("fuzzed connection killed")
        if cfg.mode == MODE_DELAY:
            if cfg.prob_sleep > 0 and self._rng.random() < cfg.prob_sleep:
                await asyncio.sleep(
                    self._rng.uniform(0, cfg.max_delay_ms / 1000.0)
                )
            return False
        # drop mode
        r = self._rng.random()
        if r < cfg.prob_drop_conn:
            self._dead = True
            self._sconn.close()
            raise ConnectionError("fuzzed connection killed")
        if r < cfg.prob_drop_conn + cfg.prob_drop_rw:
            return True
        return False

    async def write_msg(self, data: bytes) -> int:
        if await self._fuzz():
            self.dropped_writes += 1
            return len(data)  # lie: bytes vanish on the floor
        return await self._sconn.write_msg(data)

    async def read_chunk(self) -> bytes:
        while True:
            chunk = await self._sconn.read_chunk()
            if await self._fuzz():
                self.dropped_reads += 1
                continue  # swallow this chunk, keep reading
            return chunk

    async def read_msg(self) -> bytes:
        return await self.read_chunk()

    def close(self) -> None:
        self._sconn.close()


def maybe_fuzz(sconn, config: Optional[FuzzConnConfig]):
    """Wrap when fuzzing is enabled (transport hook)."""
    if config is not None and config.enable:
        return FuzzedConnection(sconn, config)
    return sconn
