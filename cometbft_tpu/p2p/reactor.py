"""Reactor base class (reference p2p/base_reactor.go:15).

A reactor owns a set of channels and reacts to peer lifecycle +
messages. All callbacks run on the switch's event loop; reactors spawn
their own gossip tasks per peer as needed.
"""

from __future__ import annotations

from typing import List, Optional

from .node_info import ChannelDescriptor
from .peer import Peer


class Reactor:
    name = "reactor"

    def __init__(self):
        self.switch = None

    def get_channels(self) -> List[ChannelDescriptor]:
        return []

    def set_switch(self, switch) -> None:
        self.switch = switch

    async def start(self) -> None:
        pass

    async def stop(self) -> None:
        pass

    def add_peer(self, peer: Peer) -> None:
        """Peer connected & handshaken; spawn gossip tasks here."""

    def remove_peer(self, peer: Peer, reason: Optional[Exception]) -> None:
        """Peer disconnected; tear down per-peer state."""

    def receive(self, chan_id: int, peer: Peer, msg: bytes) -> None:
        """A complete message arrived on one of our channels."""
