"""Peer exchange (PEX) + address book (reference p2p/pex/pex_reactor.go,
p2p/pex/addrbook.go).

AddrBook: known peer addresses split into NEW (heard about) and OLD
(connected successfully) buckets, persisted as JSON, with attempt/
success bookkeeping. PexReactor (channel 0x00): answers address
requests from the book, learns addresses from responses, and crawls —
dialing book addresses whenever the switch is below its outbound
target. Seed mode answers one request then disconnects the peer
(reference pex_reactor.go seed crawling)."""

from __future__ import annotations

import asyncio

from ..utils.tasks import spawn
import json
import os
import random
import time
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .node_info import ChannelDescriptor
from .reactor import Reactor

PEX_CHANNEL = 0x00

MSG_PEX_REQUEST = 0x01
MSG_PEX_RESPONSE = 0x02

MAX_ADDRS_PER_RESPONSE = 250
CRAWL_INTERVAL_S = 5.0
REQUEST_INTERVAL_S = 30.0
MAX_ATTEMPTS = 10
MAX_BOOK_SIZE = 5000  # reference addrbook bucket caps analog
# persisted attempt counters age out: an address whose LAST attempt
# is older than this reloads with a clean counter — without
# forgiveness, a never-connected entry that crossed MAX_ATTEMPTS
# would be is_bad FOREVER across restarts (excluded from crawl and
# selection, re-learnable only by an inbound conn). The pre-persist
# behavior got this for free by losing the counters entirely.
FORGIVE_AFTER_S = 3600.0


@dataclass
class KnownAddress:
    addr: str  # "id@host:port"
    src: str = ""  # peer id we heard it from
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    is_old: bool = False  # promoted after a successful connection
    # conn-death bookkeeping (the self-healing plane records a
    # failure on every dial failure AND every conn death)
    failures: int = 0
    last_failure: float = 0.0

    @property
    def peer_id(self) -> str:
        return self.addr.partition("@")[0]

    @property
    def is_bad(self) -> bool:
        return self.attempts >= MAX_ATTEMPTS and not self.last_success


class AddrBook:
    """JSON-persisted address book (reference p2p/pex/addrbook.go)."""

    def __init__(self, path: Optional[str] = None, our_id: str = ""):
        self.path = path
        self.our_id = our_id
        self.addrs: Dict[str, KnownAddress] = {}  # peer_id -> ka
        if path and os.path.exists(path):
            self._load()

    # --- mutation -----------------------------------------------------

    def add_address(self, addr: str, src: str = "") -> bool:
        pid = addr.partition("@")[0]
        if not pid or pid == self.our_id:
            return False
        ka = self.addrs.get(pid)
        if ka is None:
            if len(self.addrs) >= MAX_BOOK_SIZE:
                self._evict_one()
                if len(self.addrs) >= MAX_BOOK_SIZE:
                    return False  # full of good addresses; drop new
            self.addrs[pid] = KnownAddress(addr=addr, src=src)
            return True
        if not ka.is_old and addr != ka.addr:
            ka.addr = addr  # newer routing info for a NEW address
            ka.attempts = 0  # a fresh address deserves fresh dials
        elif (
            ka.is_old
            and addr != ka.addr
            and ka.last_failure > ka.last_success
        ):
            # the PROVEN address is now failing (conn died / dials
            # miss): re-learned routing info wins, or a moved peer's
            # stale entry would shadow its new address forever and
            # the reconnect plane would redial the dead one
            ka.addr = addr
            ka.attempts = 0
        return False

    def _evict_one(self) -> None:
        """Drop the least valuable entry: bad first, then the oldest
        never-connected NEW address."""
        worst = None
        for pid, a in self.addrs.items():
            if a.is_bad:
                worst = pid
                break
            if not a.is_old and (
                worst is None or a.last_attempt < self.addrs[worst].last_attempt
            ):
                worst = pid
        if worst is not None:
            del self.addrs[worst]

    def mark_attempt(self, peer_id: str) -> None:
        ka = self.addrs.get(peer_id)
        if ka:
            ka.attempts += 1
            ka.last_attempt = time.time()

    def mark_good(self, peer_id: str, addr: str = "") -> None:
        ka = self.addrs.get(peer_id)
        if ka is None and addr:
            ka = self.addrs[peer_id] = KnownAddress(addr=addr)
        if ka:
            if addr and ka.addr != addr:
                # a LIVE connection at this address is the strongest
                # routing evidence there is — it beats any older entry
                ka.addr = addr
            ka.attempts = 0
            ka.last_success = time.time()
            ka.is_old = True

    def mark_failed(self, peer_id: str, addr: str = "") -> None:
        """A dial failed or a live conn died (the reconnect plane's
        conn-death hook). Creates the entry when ``addr`` is given so
        a persistent peer that was never PEX-learned still accumulates
        health history."""
        ka = self.addrs.get(peer_id)
        if ka is None and addr:
            ka = self.addrs[peer_id] = KnownAddress(addr=addr)
        if ka:
            ka.failures += 1
            ka.last_failure = time.time()

    def remove(self, peer_id: str) -> None:
        self.addrs.pop(peer_id, None)

    # --- selection ----------------------------------------------------

    def selection(self, limit: int = MAX_ADDRS_PER_RESPONSE) -> List[str]:
        """Biased random sample for PEX responses (reference
        GetSelection: mix of old + new)."""
        pool = [a for a in self.addrs.values() if not a.is_bad]
        random.shuffle(pool)
        pool.sort(key=lambda a: not a.is_old)  # old first, then new
        take = pool[: limit // 2] + [
            a for a in pool[limit // 2:] if not a.is_old
        ][: limit // 2]
        return [a.addr for a in take[:limit]]

    def pick_to_dial(self, exclude: set, n: int) -> List[str]:
        cands = [
            a
            for pid, a in self.addrs.items()
            if pid not in exclude and not a.is_bad
            and time.time() - a.last_attempt > 10.0 * (a.attempts + 1)
        ]
        # new-bucket bias like the reference's crawl
        random.shuffle(cands)
        return [a.addr for a in cands[:n]]

    def size(self) -> int:
        return len(self.addrs)

    # --- persistence --------------------------------------------------

    def save(self) -> None:
        if not self.path:
            return
        # the FULL bookkeeping persists: a restarted node's reconnect
        # plane and crawl biasing resume from real dial history, not a
        # wiped slate (attempts/last_attempt previously evaporated
        # across restarts, resetting pick_to_dial's backoff gating)
        data = [
            {
                "addr": a.addr,
                "src": a.src,
                "attempts": a.attempts,
                "last_attempt": a.last_attempt,
                "last_success": a.last_success,
                "is_old": a.is_old,
                "failures": a.failures,
                "last_failure": a.last_failure,
            }
            for a in self.addrs.values()
        ]
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"addrs": data}, f)
        os.replace(tmp, self.path)

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                data = json.load(f)
            now = time.time()
            for d in data.get("addrs", []):
                ka = KnownAddress(
                    addr=d["addr"],
                    src=d.get("src", ""),
                    attempts=d.get("attempts", 0),
                    last_attempt=d.get("last_attempt", 0.0),
                    last_success=d.get("last_success", 0.0),
                    is_old=d.get("is_old", False),
                    failures=d.get("failures", 0),
                    last_failure=d.get("last_failure", 0.0),
                )
                if now - ka.last_attempt > FORGIVE_AFTER_S:
                    # aged-out failure history (FORGIVE_AFTER_S):
                    # the entry gets a fresh chance; failures/
                    # last_failure stay for diagnostics
                    ka.attempts = 0
                self.addrs[ka.peer_id] = ka
        except Exception:
            traceback.print_exc()


class PexReactor(Reactor):
    name = "pex"

    def __init__(
        self,
        book: AddrBook,
        seed_mode: bool = False,
        target_outbound: int = 10,
    ):
        super().__init__()
        self.book = book
        self.seed_mode = seed_mode
        self.target_outbound = target_outbound
        self._crawl_task: Optional[asyncio.Task] = None
        self._last_request: Dict[str, float] = {}
        self._requested: set = set()  # peers we asked (expect response)

    def get_channels(self):
        return [
            ChannelDescriptor(PEX_CHANNEL, priority=1, max_msg_size=1 << 16)
        ]

    async def start(self) -> None:
        self._crawl_task = asyncio.create_task(self._crawl_routine())

    async def stop(self) -> None:
        if self._crawl_task:
            self._crawl_task.cancel()
        self.book.save()

    # --- peers --------------------------------------------------------

    def add_peer(self, peer) -> None:
        # every live peer is a GOOD address
        if peer.node_info.listen_addr:
            self.book.mark_good(
                peer.peer_id,
                f"{peer.peer_id}@{peer.node_info.listen_addr}",
            )
        if peer.outbound and not self.seed_mode:
            self._request_addrs(peer)

    def remove_peer(self, peer, reason) -> None:
        self._requested.discard(peer.peer_id)
        self._last_request.pop(peer.peer_id, None)

    def _request_addrs(self, peer) -> None:
        now = time.monotonic()
        if now - self._last_request.get(peer.peer_id, 0) < REQUEST_INTERVAL_S:
            return
        self.request_now(peer)

    def request_now(self, peer) -> None:
        """Rate-limit-bypassing address request: the switch calls this
        on every dial success while the node is STARVING (zero peers
        past the starvation threshold) so a rejoining minority
        re-learns moved/healed addresses immediately instead of
        waiting out REQUEST_INTERVAL_S."""
        self._last_request[peer.peer_id] = time.monotonic()
        self._requested.add(peer.peer_id)
        peer.try_send(PEX_CHANNEL, bytes([MSG_PEX_REQUEST]))

    # --- wire ---------------------------------------------------------

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        mtype = msg[0]
        if mtype == MSG_PEX_REQUEST:
            addrs = self.book.selection()
            # advertise ourselves too? peers already know us. Send book.
            peer.try_send(
                PEX_CHANNEL,
                bytes([MSG_PEX_RESPONSE])
                + json.dumps(addrs).encode(),
            )
            if self.seed_mode:
                # seeds serve addresses then hang up (reference
                # pex_reactor.go:~seed mode)
                spawn(self.switch.stop_peer_gracefully(peer))
        elif mtype == MSG_PEX_RESPONSE:
            if peer.peer_id not in self._requested:
                # unsolicited response is a protocol violation
                # (reference ErrUnsolicitedList)
                self.switch.stop_peer_for_error(
                    peer, ValueError("unsolicited PEX response")
                )
                return
            self._requested.discard(peer.peer_id)
            try:
                addrs = json.loads(msg[1:].decode())
            except Exception:
                self.switch.stop_peer_for_error(
                    peer, ValueError("bad PEX response")
                )
                return
            for a in addrs[:MAX_ADDRS_PER_RESPONSE]:
                if isinstance(a, str) and "@" in a:
                    self.book.add_address(a, src=peer.peer_id)
        else:
            raise ValueError(f"unknown pex msg type {mtype}")

    # --- crawling -----------------------------------------------------

    async def _crawl_routine(self) -> None:
        try:
            while True:
                await asyncio.sleep(CRAWL_INTERVAL_S)
                sw = self.switch
                if sw is None:
                    continue
                have = sw.num_peers()
                if have >= self.target_outbound:
                    # refresh the book occasionally from a random peer
                    peers = list(sw.peers.values())
                    if peers and not self.seed_mode:
                        self._request_addrs(random.choice(peers))
                    continue
                exclude = set(sw.peers) | sw.banned | {self.book.our_id}
                for addr in self.book.pick_to_dial(
                    exclude, self.target_outbound - have
                ):
                    pid = addr.partition("@")[0]
                    self.book.mark_attempt(pid)
                    try:
                        await sw.dial_peer(addr)
                        self.book.mark_good(pid, addr)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        pass  # crawl dials fail routinely
                self.book.save()
        except asyncio.CancelledError:
            raise
        except Exception:
            traceback.print_exc()
