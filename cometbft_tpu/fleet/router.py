"""SessionRouter: the one admission seam in front of N replicas
(ISSUE 19, docs/FLEET.md).

Every serving request — websocket subscription, light session, indexed
read — enters the fleet HERE:

- **admission** via the InstrumentedGate contract (obs/queues.py):
  ``try_enter`` never blocks the loop, overload is a counted shed, and
  the gate rides the obs QueueRegistry as ``fleet.route`` so health
  sees router backpressure like any bounded queue;
- **placement** least-loaded across serviceable replicas;
- **consistency tokens**: a request carrying token H only lands on a
  replica whose served height ≥ H — the indexer's sealed-vs-flushed
  ``idx:last`` barrier generalized cross-replica. If no replica
  satisfies H the router WAITS the most advanced replica's height
  barrier (bounded) or refuses (``StaleReadError``); it never serves
  stale;
- **lag-aware shedding**: a follower stalled past
  ``[fleet] max_lag_heights`` is drained and marked degraded — only
  ITS clients are shed; the rest of the fleet is untouched;
- **failover**: on replica death mid-stream every session is
  re-admitted elsewhere with ZERO lost commits — CommitWaiterMap-style
  lossless height-keyed resume: the session replays
  ``last_delivered+1..`` from the store before going live, and the
  live stream is spliced behind the replay through a bounded buffer
  (membership snapshots in follower.ReplicaFanout are per height, so
  the buffer always starts at a clean height boundary).
"""

from __future__ import annotations

import asyncio
import json
import re
import time
from typing import Dict, List, Optional, Set

from ..obs.queues import InstrumentedGate
from ..trace import NOOP
from ..utils.log import get_logger
from ..utils.tasks import spawn
from .follower import event_payload, height_events
from ..rpc.fanout import _event_attrs

_log = get_logger("fleet.router")

# bounded wait for the watchdog task to unwind on close (ASY110)
WATCH_STOP_WAIT_S = 2.0

# replay-splice safety bounds: a resume that can't converge inside
# these is SHED (honest bound), never silently truncated
REPLAY_MAX_LEGS = 32
REPLAY_BUFFER_MAX = 65536

# first "height" key in a frame is the block/tx height for every frame
# shape rpc/fanout.py emits (block header height for NewBlock, TxResult
# height for Tx) — the hub-path fallback when delivery has no
# on_height signal (fleet.follower.NodeReplica)
_HEIGHT_RE = re.compile(r'"height": ?"(\d+)"')


class FleetOverloadError(Exception):
    """Router at its session bound or no serviceable replica."""


class StaleReadError(Exception):
    """Consistency token unsatisfiable: no replica at or past the
    token height within the barrier wait — the request must be
    retried/redirected, NEVER served below its token."""


class RoutedSession:
    """One routed subscription: the pipe between a replica's delivery
    plane and the client sink. Tracks ``last_delivered`` (the lossless
    height-keyed resume cursor) and buffers live frames during a
    failover replay so the splice is gap-free AND duplicate-free."""

    __slots__ = (
        "sink",
        "query_str",
        "query",
        "sub_id",
        "_prefix",
        "last_delivered",
        "closed",
        "close_reason",
        "parse_heights",
        "resumes",
        "_buffer",
        "_replaying",
        "_pending_height",
        "_router",
    )

    def __init__(self, sink, query_str: str, query, sub_id):
        self.sink = sink
        self.query_str = query_str
        self.query = query
        self.sub_id = sub_id
        # identical envelope to rpc.fanout.FanoutSubscriber so routed
        # frames are byte-compatible with hub frames
        self._prefix = (
            '{"jsonrpc": "2.0", "id": '
            + json.dumps(sub_id)
            + ', "result": '
        )
        self.last_delivered = 0
        self.closed = False
        self.close_reason = ""
        self.parse_heights = False
        self.resumes = 0
        self._buffer: List[str] = []
        self._replaying = False
        self._pending_height = 0
        self._router = None

    # --- delivery-plane surface ---------------------------------------

    async def send_str(self, frame: str) -> None:
        if self.closed:
            raise ConnectionError("session closed")
        if self._replaying:
            if len(self._buffer) >= REPLAY_BUFFER_MAX:
                raise ConnectionError("replay buffer overflow")
            self._buffer.append(frame)
            return
        await self.sink.send_str(frame)
        if self.parse_heights:
            m = _HEIGHT_RE.search(frame)
            if m:
                h = int(m.group(1))
                if h > self.last_delivered:
                    self.last_delivered = h

    def on_height(self, height: int) -> None:
        """Replica-paced delivery completed ``height`` for this
        session (follower.ReplicaFanout)."""
        if self._replaying:
            if height > self._pending_height:
                self._pending_height = height
        elif height > self.last_delivered:
            self.last_delivered = height

    def on_send_failed(self) -> None:
        """The delivery plane saw this session's sink raise: degrade
        THIS session only — the router reaps it off-loop."""
        self.closed = True
        self.close_reason = self.close_reason or "send_failed"
        r = self._router
        if r is not None:
            r._note_failed(self)

    # --- replay splice ------------------------------------------------

    def begin_replay(self) -> None:
        self._replaying = True
        self._pending_height = 0

    async def end_replay(self, replayed_through: int) -> None:
        """Flush the live frames buffered during replay, dropping the
        ones the replay already covered (height ≤ ``replayed_through``
        — per-height membership snapshots guarantee the buffer starts
        at a clean height boundary, so this is exact)."""
        buffered, self._buffer = self._buffer, []
        self._replaying = False
        for frame in buffered:
            m = _HEIGHT_RE.search(frame)
            if m and int(m.group(1)) <= replayed_through:
                continue
            await self.sink.send_str(frame)
        if self._pending_height > self.last_delivered:
            self.last_delivered = self._pending_height
        self._pending_height = 0


class SessionRouter:
    """N replicas behind one admission + placement + failover seam."""

    def __init__(
        self,
        replicas: List,
        *,
        store_source=None,
        max_sessions: int = 4096,
        admit_timeout_s: float = 0.25,
        max_lag_heights: int = 8,
        lag_poll_s: float = 0.1,
        token_wait_s: float = 2.0,
        resume_replay_max: int = 512,
        tracer=NOOP,
    ):
        self.replicas = list(replicas)
        self.store_source = store_source
        self.tracer = tracer
        self.admit_timeout_s = admit_timeout_s
        self.max_lag_heights = max_lag_heights
        self.lag_poll_s = lag_poll_s
        self.token_wait_s = token_wait_s
        self.resume_replay_max = resume_replay_max
        self.gate = InstrumentedGate(max_sessions, name="fleet.route")
        self._sessions: Dict[RoutedSession, object] = {}
        self._degraded: Set[int] = set()  # id(replica)
        self._failed: List[RoutedSession] = []
        self._watch_task: Optional[asyncio.Future] = None
        self._wake: Optional[asyncio.Event] = None
        self._drain_tasks: List[asyncio.Future] = []
        self.failovers = 0
        self.sessions_resumed = 0
        self.sheds_lag = 0
        self.sheds_failover = 0
        self.tokens_issued = 0
        for r in self.replicas:
            r.on_death = self._on_replica_death

    # --- lifecycle ----------------------------------------------------

    async def start(self) -> None:
        if self._watch_task is None:
            self._wake = asyncio.Event()
            self._watch_task = spawn(
                self._watch(), name="fleet-router-watch"
            )

    async def close(self) -> None:
        t, self._watch_task = self._watch_task, None
        if t is not None and not t.done():
            t.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(t, return_exceptions=True),
                    WATCH_STOP_WAIT_S,
                )
            except asyncio.TimeoutError:
                pass
        for dt in self._drain_tasks:
            if not dt.done():
                dt.cancel()
        self._drain_tasks.clear()
        for sess in list(self._sessions):
            await self._close_session(sess, "router_closed")

    # --- admission + placement ----------------------------------------

    def _serviceable(self, *, need_light: bool = False) -> List:
        return [
            r
            for r in self.replicas
            if r.alive
            and not r.draining
            and id(r) not in self._degraded
            and (not need_light or r.light_plane is not None)
        ]

    async def _pick(
        self, token: Optional[int] = None, *, need_light: bool = False
    ):
        elig = self._serviceable(need_light=need_light)
        if not elig:
            raise FleetOverloadError("no serviceable replica")
        if token:
            sat = [r for r in elig if r.served_height() >= token]
            if sat:
                return min(sat, key=lambda r: r.members())
            # nobody is at the token yet: wait the MOST ADVANCED
            # replica's height barrier (bounded) — route-away or
            # wait, never stale
            best = max(elig, key=lambda r: r.served_height())
            ok = await best.wait_height(token, self.token_wait_s)
            if not ok or not best.alive:
                raise StaleReadError(
                    f"no replica reached token height {token} "
                    f"within {self.token_wait_s}s"
                )
            return best
        return min(elig, key=lambda r: r.members())

    async def subscribe(
        self,
        sink,
        query_str: str,
        query=None,
        *,
        sub_id=None,
        token: Optional[int] = None,
    ) -> RoutedSession:
        """Admit + place one event subscription."""
        if query is None:
            from ..utils.pubsub_query import parse as parse_query

            query = parse_query(query_str)
        span = self.tracer.span(
            "fleet.route", "fleet", kind="subscribe"
        )
        with span:
            if not self.gate.try_enter():
                span.set(shed=True)
                raise FleetOverloadError(
                    "router at its session bound; retry"
                )
            try:
                replica = await self._pick(token)
            except BaseException:
                self.gate.exit()
                raise
            sess = RoutedSession(
                sink,
                query_str,
                query,
                sub_id if sub_id is not None else len(self._sessions),
            )
            sess._router = self
            sess.parse_heights = getattr(
                replica, "HUB_DELIVERY", False
            )
            if token:
                sess.last_delivered = 0
            replica.attach(sess)
            self._sessions[sess] = replica
            span.set(replica=getattr(replica, "name", "?"))
            return sess

    async def unsubscribe(self, sess: RoutedSession) -> None:
        await self._close_session(sess, "unsubscribed")

    async def route_read(self, token: Optional[int] = None):
        """Pick a replica for a one-shot read under a consistency
        token: the returned replica's served height is ≥ token (the
        read-your-writes guarantee), or StaleReadError."""
        span = self.tracer.span("fleet.route", "fleet", kind="read")
        with span:
            replica = await self._pick(token)
            span.set(replica=getattr(replica, "name", "?"))
            return replica

    def route_light(
        self, token: Optional[int] = None, timeout_s: Optional[float] = None
    ):
        """Thread-facing placement for light sessions (the serving
        plane is the thread seam — light/serving.py): returns a
        replica whose plane to open a session on, honoring the token
        with a bounded poll-wait. Never returns a replica below the
        token."""
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.token_wait_s
        )
        while True:
            elig = self._serviceable(need_light=True)
            sat = [
                r
                for r in elig
                if not token or r.served_height() >= token
            ]
            if sat:
                return min(
                    sat, key=lambda r: r.light_plane.active_sessions()
                )
            if time.monotonic() >= deadline:
                if not elig:
                    raise FleetOverloadError(
                        "no serviceable light replica"
                    )
                raise StaleReadError(
                    f"no light replica reached token height {token}"
                )
            time.sleep(0.005)

    def serve_light(
        self, height: int, token: Optional[int] = None
    ):
        """One routed light request (thread-facing): placement here,
        admission + single-flight verify on the replica's own plane."""
        replica = self.route_light(token)
        return replica.light_plane.serve(height)

    def issue_token(self) -> int:
        """Read-your-writes token: the committee head as this router
        sees it — any write committed by now is covered."""
        self.tokens_issued += 1
        return self._head()

    def _head(self) -> int:
        if self.store_source is not None:
            return self.store_source.height()
        alive = [r.served_height() for r in self.replicas if r.alive]
        return max(alive) if alive else 0

    # --- lag watchdog + failover --------------------------------------

    def _on_replica_death(self, replica) -> None:
        if self._wake is not None:
            self._wake.set()

    def _note_failed(self, sess: RoutedSession) -> None:
        self._failed.append(sess)
        if self._wake is not None:
            self._wake.set()

    async def _watch(self) -> None:
        while True:
            try:
                await asyncio.wait_for(
                    self._wake.wait(), self.lag_poll_s
                )
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            try:
                await self._reap_failed()
                self._check_lag()
                for r in self.replicas:
                    if not r.alive and any(
                        rep is r for rep in self._sessions.values()
                    ):
                        await self._failover(r)
            except asyncio.CancelledError:
                raise
            except Exception:
                import traceback

                traceback.print_exc()

    async def _reap_failed(self) -> None:
        failed, self._failed = self._failed, []
        for sess in failed:
            if sess in self._sessions:
                await self._close_session(sess, "send_failed")

    def _check_lag(self) -> None:
        head = self._head()
        for r in self.replicas:
            if not r.alive:
                continue
            lag = head - r.served_height()
            if id(r) not in self._degraded:
                if lag > self.max_lag_heights:
                    self._degrade(r, lag)
            elif lag <= max(1, self.max_lag_heights // 2):
                # caught back up: rotate back in
                self._degraded.discard(id(r))
                r.resume_serving()
                _log.info(
                    "replica recovered",
                    replica=getattr(r, "name", "?"),
                    lag=lag,
                )

    def _degrade(self, replica, lag: int) -> None:
        """A stalled follower degrades ONLY its own clients: mark it
        out of placement, drain its serving plane, shed its sessions
        (they re-admit through the front door and land elsewhere)."""
        self._degraded.add(id(replica))
        _log.info(
            "replica degraded (lag shed)",
            replica=getattr(replica, "name", "?"),
            lag=lag,
            max_lag=self.max_lag_heights,
        )
        if replica.light_plane is not None:
            self._drain_tasks.append(
                spawn(
                    asyncio.to_thread(replica.light_plane.drain, 5.0),
                    name="fleet-drain",
                )
            )
        mine = [
            s for s, rep in self._sessions.items() if rep is replica
        ]
        for sess in mine:
            self.sheds_lag += 1
            spawn(
                self._close_session(sess, "shed_lag"),
                name="fleet-shed",
            )

    async def _failover(self, replica) -> None:
        """Replica died mid-stream: re-admit every one of its
        sessions elsewhere with zero lost commits (store replay up to
        the live splice)."""
        sessions = [
            s for s, rep in self._sessions.items() if rep is replica
        ]
        if not sessions:
            return
        self.failovers += 1
        span = self.tracer.span(
            "fleet.failover",
            "fleet",
            replica=getattr(replica, "name", "?"),
            sessions=len(sessions),
        )
        with span:
            resumed = 0
            for sess in sessions:
                targets = [
                    r
                    for r in self._serviceable()
                    if r is not replica
                ]
                if not targets or sess.closed:
                    await self._close_session(sess, "failover_shed")
                    self.sheds_failover += 1
                    continue
                target = min(targets, key=lambda r: r.members())
                if await self._resume_on(sess, target):
                    self._sessions[sess] = target
                    sess.resumes += 1
                    self.sessions_resumed += 1
                    resumed += 1
                else:
                    await self._close_session(sess, "failover_shed")
                    self.sheds_failover += 1
            span.set(resumed=resumed)

    async def _resume_on(self, sess: RoutedSession, target) -> bool:
        """Lossless height-keyed resume: attach live (buffering),
        replay ``last_delivered+1..`` from the store, splice."""
        src = self.store_source
        if src is None:
            # no store to replay from: live-only re-admit is LOSSY —
            # refuse (the caller sheds; the client re-subscribes with
            # its own resume logic)
            return False
        gap = max(0, src.height() - sess.last_delivered)
        if gap > self.resume_replay_max:
            return False
        sess.begin_replay()
        target.attach(sess)
        sess.parse_heights = getattr(target, "HUB_DELIVERY", False)
        cur = sess.last_delivered
        end = cur
        try:
            for _ in range(REPLAY_MAX_LEGS):
                end = max(end, target.served_height())
                while cur < end:
                    h = cur + 1
                    block = src.load_block(h)
                    if block is None:
                        # pruned below the resume cursor: lossless
                        # replay is impossible — shed honestly
                        raise LookupError(h)
                    for e in height_events(
                        block, getattr(src, "results_fn", None)
                    ):
                        attrs = _event_attrs(e)
                        if not sess.query.matches(attrs):
                            continue
                        await sess.sink.send_str(
                            sess._prefix
                            + event_payload(e, sess.query_str, attrs)
                            + "}"
                        )
                    cur = h
                    sess.last_delivered = h
                    await asyncio.sleep(0)
                if target.served_height() <= end:
                    break
            else:
                raise LookupError("replay could not converge")
            await sess.end_replay(end)
            return True
        except asyncio.CancelledError:
            raise
        except Exception:
            await target.detach_member(sess)
            sess._replaying = False
            sess._buffer.clear()
            return False

    # --- teardown helpers ---------------------------------------------

    async def _close_session(
        self, sess: RoutedSession, reason: str
    ) -> None:
        replica = self._sessions.pop(sess, None)
        if replica is not None:
            try:
                await replica.detach_member(sess)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            self.gate.exit()
        sess.closed = True
        sess.close_reason = sess.close_reason or reason

    # --- obs / introspection ------------------------------------------

    def register_queues(self, registry) -> None:
        """Expose router admission in an obs QueueRegistry (the same
        contract every bounded plane follows)."""
        registry.register("fleet.route", self.gate.stats)

    def fleet_status(self) -> dict:
        head = self._head()
        reps = []
        for r in self.replicas:
            st = r.status()
            st["lag_heights"] = (
                max(0, head - r.served_height()) if r.alive else None
            )
            st["degraded"] = id(r) in self._degraded
            reps.append(st)
        return {
            "head": head,
            "sessions": len(self._sessions),
            "admission": self.gate.stats(),
            "failovers": self.failovers,
            "sessions_resumed": self.sessions_resumed,
            "sheds": {
                "admit": self.gate.stats()["dropped"],
                "lag": self.sheds_lag,
                "failover": self.sheds_failover,
            },
            "tokens_issued": self.tokens_issued,
            "replicas": reps,
        }
