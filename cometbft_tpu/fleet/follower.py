"""Follower replicas: the serving-fleet deployment shape (ISSUE 19).

A ``FollowerNode`` is a NON-validator: no privval, no mempool
proposing — it tail-follows the committee's committed chain (the
blocksync shape, in-process: a bounded tail loop applying heights in
order from a commit source) and runs the full read stack per replica:

- ``ReplicaFanout`` — height-batched, replica-paced event delivery to
  routed subscriber sessions. Unlike the validator-side FanoutHub
  (rpc/fanout.py), a follower needs no per-subscriber elastic
  queue+writer-task machinery: the tail applies heights at its own
  pace, delivery for a height completes before the tail advances, and
  a client that cannot keep up is SHED to the router — which can
  re-admit it elsewhere and replay the gap from the store losslessly
  (the failover path doubles as slow-client recovery). That trades
  12µs/frame of queue+task indirection for ~2µs of splice+send, which
  is what lets a fleet's aggregate delivered-frames/s scale past the
  single-hub record (docs/PERF.md "Serving fleet").
- ``LightServingPlane`` (light/serving.py) — optional per replica,
  with a shared-process ``VerifiedHeaderCache`` so single-flight
  verification holds FLEET-wide, not per replica.
- the indexer read barrier — when an ``IndexerService`` rides the
  replica, ``read_barrier()`` awaits its sealed-vs-flushed barrier so
  indexed reads are read-your-writes per replica; the router's
  consistency tokens generalize the same barrier cross-replica.

``NodeReplica`` adapts a real running ``node.Node`` (validator or
blocksync follower) to the same replica surface so the router can
front mixed deployments.
"""

from __future__ import annotations

import asyncio
import json
from typing import Callable, Dict, List, Optional, Set

from ..rpc.fanout import _event_attrs, _event_json
from ..trace import NOOP
from ..types import events as ev
from ..utils.log import get_logger
from ..utils.tasks import spawn

_log = get_logger("fleet.follower")

# bounded wait for the cancelled tail task to unwind on kill/stop
# (ASY110): a wedged source read must not hang fleet teardown
TAIL_STOP_WAIT_S = 2.0

# cooperative-yield stride inside a height's delivery batch: direct
# sends to in-process sinks don't otherwise yield, and the tail must
# not monopolize the loop for a 10k-subscriber height
YIELD_EVERY = 1024


def _tx_result_empty():
    from ..abci import types as abci

    return abci.ExecTxResult(code=0)


def height_events(
    block, results_fn: Optional[Callable] = None
) -> List[ev.Event]:
    """The canonical event bundle for one committed height, built
    FROM THE STORE BLOCK — used by both the live tail and failover
    replay so a replayed frame is byte-identical to the live frame it
    stands in for (rpc/fanout.py frame shape)."""
    h = block.header.height
    out = [
        ev.Event(
            ev.EVENT_NEW_BLOCK,
            {"block": block, "block_id": None, "result_events": []},
            {"height": str(h)},
        )
    ]
    txs = block.data.txs if block.data is not None else []
    for i, tx in enumerate(txs):
        import hashlib

        res = (
            results_fn(block, i, tx)
            if results_fn is not None
            else _tx_result_empty()
        )
        out.append(
            ev.Event(
                ev.EVENT_TX,
                {"height": h, "index": i, "tx": tx, "result": res},
                {"hash": hashlib.sha256(tx).hexdigest()},
            )
        )
    return out


def event_payload(e: ev.Event, query_str: str, attrs=None) -> str:
    """One group-shared payload, identical in structure and key order
    to FanoutHub._deliver's encoding (splice ``prefix + payload + '}'``
    per subscriber)."""
    if attrs is None:
        attrs = _event_attrs(e)
    return json.dumps(
        {"query": query_str, "data": _event_json(e), "events": attrs}
    )


# --- commit sources ---------------------------------------------------


class StoreSource:
    """Tail source over a committee node's block store (the in-process
    stand-in for blocksync tail-follow: same data, same ordering, no
    sockets). ``results_fn(block, i, tx)`` supplies ExecTxResults for
    Tx events when the deployment has them (followers replaying
    finalize responses); default is an empty result."""

    def __init__(self, block_store, results_fn=None):
        self._store = block_store
        self.results_fn = results_fn

    def height(self) -> int:
        return self._store.height()

    def base(self) -> int:
        try:
            return self._store.base()
        except Exception:
            return 1

    def load_block(self, height: int):
        return self._store.load_block(height)

    async def wait_beyond(self, height: int, timeout_s: float) -> None:
        """Park until the source head passes ``height`` (bounded);
        store-backed sources poll — stream sources override with a
        real wakeup."""
        deadline = asyncio.get_running_loop().time() + timeout_s
        while self.height() <= height:
            if asyncio.get_running_loop().time() >= deadline:
                return
            await asyncio.sleep(0.005)


class StreamSource(StoreSource):
    """In-process committee feed for tests/bench: blocks are pushed
    via ``advance`` and tails wake immediately (no poll latency)."""

    def __init__(self, results_fn=None):
        self._blocks: Dict[int, object] = {}
        self._height = 0
        self.results_fn = results_fn
        self._advanced: asyncio.Event = asyncio.Event()

    def height(self) -> int:
        return self._height

    def base(self) -> int:
        return 1

    def load_block(self, height: int):
        return self._blocks.get(height)

    def advance(self, block) -> None:
        h = block.header.height
        self._blocks[h] = block
        if h > self._height:
            self._height = h
        self._advanced.set()

    async def wait_beyond(self, height: int, timeout_s: float) -> None:
        if self.height() > height:
            return
        self._advanced.clear()
        if self.height() > height:  # advance raced the clear
            return
        try:
            await asyncio.wait_for(self._advanced.wait(), timeout_s)
        except asyncio.TimeoutError:
            pass


# --- replica-paced fan-out --------------------------------------------


class _FleetGroup:
    __slots__ = ("query_str", "query", "members")

    def __init__(self, query_str: str, query):
        self.query_str = query_str
        self.query = query
        self.members: Set = set()


class ReplicaFanout:
    """Height-batched delivery to routed sessions: attrs once per
    event, ONE encode per (event, query group), one direct-awaited
    ``send_str`` per member frame. Membership snapshots are taken per
    HEIGHT (at ``deliver`` entry), so a session attached mid-height
    receives nothing for that height — its first live height is a
    clean boundary, which is what makes the router's replay splice
    lossless (router.py)."""

    def __init__(self, name: str = "", tracer=NOOP):
        self.name = name
        self.tracer = tracer
        self._groups: Dict[str, _FleetGroup] = {}
        self.encodes = 0
        self.delivered = 0
        self.dropped = 0  # sends that raised: member failed mid-frame

    def attach(self, member) -> None:
        g = self._groups.get(member.query_str)
        if g is None:
            g = _FleetGroup(member.query_str, member.query)
            self._groups[member.query_str] = g
        g.members.add(member)

    def detach(self, member) -> None:
        g = self._groups.get(member.query_str)
        if g is not None:
            g.members.discard(member)
            if not g.members:
                self._groups.pop(member.query_str, None)

    def members(self) -> int:
        return sum(len(g.members) for g in self._groups.values())

    async def deliver(self, events: List[ev.Event], height: int) -> None:
        """Deliver one height's event bundle to every member attached
        at entry; advance each surviving member's ``on_height`` only
        after ALL its frames for the height went out."""
        snapshot = [
            (g, list(g.members))
            for g in list(self._groups.values())
            if g.members
        ]
        if not snapshot:
            return
        failed: Set = set()
        sends = 0
        for e in events:
            attrs = _event_attrs(e)  # once per event
            for g, members in snapshot:
                if not g.query.matches(attrs):
                    continue
                payload = event_payload(e, g.query_str, attrs)
                self.encodes += 1
                for m in members:
                    if m in failed:
                        continue
                    try:
                        await m.send_str(m._prefix + payload + "}")
                        self.delivered += 1
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        # a dead sink degrades ITS session only; the
                        # router reaps it via the on_failed callback
                        self.dropped += 1
                        failed.add(m)
                    sends += 1
                    if sends % YIELD_EVERY == 0:
                        await asyncio.sleep(0)
        for g, members in snapshot:
            for m in members:
                if m in failed:
                    self.detach(m)
                    m.on_send_failed()
                else:
                    m.on_height(height)

    def stats(self) -> dict:
        return {
            "groups": len(self._groups),
            "members": self.members(),
            "encodes": self.encodes,
            "delivered": self.delivered,
            "dropped": self.dropped,
        }


# --- the follower replica ---------------------------------------------


class FollowerNode:
    """Non-validator read replica tail-following a commit source."""

    role = "follower"
    # delivery is replica-paced (ReplicaFanout calls on_height); the
    # router needs no frame-sniffing height fallback on this path
    HUB_DELIVERY = False

    def __init__(
        self,
        name: str,
        source,
        *,
        light_plane=None,
        indexer_service=None,
        poll_s: float = 0.05,
        tracer=NOOP,
    ):
        self.name = name
        self.source = source
        self.tracer = tracer
        self.poll_s = poll_s
        self.fanout = ReplicaFanout(name=name, tracer=tracer)
        self.light_plane = light_plane
        self.indexer_service = indexer_service
        self.alive = False
        self.stalled = False  # lag injection (tests/chaos)
        self.draining = False
        self._served = 0
        self._tail_task: Optional[asyncio.Future] = None
        self._barriers: List[tuple] = []  # (height, asyncio.Event)
        self.on_death: Optional[Callable] = None
        self.heights_applied = 0

    # --- lifecycle ----------------------------------------------------

    async def start(self, from_height: Optional[int] = None) -> None:
        """Join at the current committee head (``from_height`` pins a
        deeper starting point for tests) and tail forward."""
        if self._tail_task is not None:
            return
        self._served = (
            self.source.height() if from_height is None else from_height
        )
        self.alive = True
        self._tail_task = spawn(
            self._tail(), name=f"fleet-tail-{self.name}"
        )

    async def stop(self) -> None:
        """Graceful: stop the tail, leave serving state readable."""
        self.alive = False
        t, self._tail_task = self._tail_task, None
        if t is not None and not t.done():
            t.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(t, return_exceptions=True),
                    TAIL_STOP_WAIT_S,
                )
            except asyncio.TimeoutError:
                pass
        self._fire_barriers(dead=True)

    async def kill(self) -> None:
        """Replica death (chaos ``replica_kill``): tail torn down,
        sessions stranded mid-stream — the router's failover must
        re-admit them elsewhere with zero lost commits."""
        await self.stop()
        cb = self.on_death
        if cb is not None:
            cb(self)

    async def drain(self, timeout_s: float = 5.0) -> dict:
        """Rotate-out: stop admitting new serving work and resolve
        in-flight light requests (bounded, ASY110-clean). The tail
        keeps following so the replica can be rotated back in."""
        self.draining = True
        if self.light_plane is not None:
            return await asyncio.to_thread(
                self.light_plane.drain, timeout_s
            )
        return {"drained": True, "waited_s": 0.0}

    def resume_serving(self) -> None:
        self.draining = False
        if self.light_plane is not None:
            self.light_plane.resume()

    # --- the tail -----------------------------------------------------

    async def _tail(self) -> None:
        try:
            while True:
                applied = False
                while not self.stalled and self._served < self.source.height():
                    h = self._served + 1
                    block = self.source.load_block(h)
                    if block is None:
                        break  # pruned/not yet visible: re-poll
                    events = height_events(
                        block, getattr(self.source, "results_fn", None)
                    )
                    await self.fanout.deliver(events, h)
                    self._served = h
                    self.heights_applied += 1
                    self._fire_barriers()
                    applied = True
                if applied:
                    await asyncio.sleep(0)
                elif (
                    self.stalled
                    or self._served < self.source.height()
                ):
                    # stalled (lag injection) or the next block isn't
                    # visible yet: wait_beyond would return
                    # immediately (head already past us) — poll, don't
                    # busy-spin the shared loop
                    await asyncio.sleep(self.poll_s)
                else:
                    await self.source.wait_beyond(
                        self._served, self.poll_s
                    )
        except asyncio.CancelledError:
            raise
        except Exception:
            _log.error("follower tail died", name=self.name)
            import traceback

            traceback.print_exc()
            self.alive = False
            cb = self.on_death
            if cb is not None:
                cb(self)

    # --- height barrier (the consistency-token seam) ------------------

    def served_height(self) -> int:
        return self._served

    def lag_heights(self) -> int:
        return max(0, self.source.height() - self._served)

    async def wait_height(self, height: int, timeout_s: float) -> bool:
        """Height barrier: True once this replica has served through
        ``height``; False on timeout or replica death (the caller
        must route away, NEVER serve stale)."""
        if self._served >= height:
            return True
        if not self.alive:
            return False
        evt = asyncio.Event()
        self._barriers.append((height, evt))
        try:
            await asyncio.wait_for(evt.wait(), timeout_s)
        except asyncio.TimeoutError:
            return False
        return self._served >= height

    def _fire_barriers(self, dead: bool = False) -> None:
        if not self._barriers:
            return
        keep = []
        for height, evt in self._barriers:
            if dead or self._served >= height:
                evt.set()
            else:
                keep.append((height, evt))
        self._barriers = keep

    async def read_barrier(self, timeout_s: float = 5.0) -> None:
        """Indexed-read barrier: everything this replica has sealed is
        flushed (state/indexer.py) — per-replica read-your-writes."""
        if self.indexer_service is not None:
            await self.indexer_service.barrier(timeout_s)

    # --- session membership (router-facing) ---------------------------

    def attach(self, member) -> None:
        self.fanout.attach(member)

    async def detach_member(self, member) -> None:
        self.fanout.detach(member)

    def members(self) -> int:
        return self.fanout.members()

    # --- introspection ------------------------------------------------

    def status(self) -> dict:
        return {
            "name": self.name,
            "role": self.role,
            "alive": self.alive,
            "stalled": self.stalled,
            "draining": self.draining,
            "served_height": self._served,
            "lag_heights": self.lag_heights(),
            "sessions": self.fanout.members(),
            "fanout": self.fanout.stats(),
            "light": self.light_plane.stats()
            if self.light_plane is not None
            else None,
        }


class NodeReplica:
    """Adapter: a real running ``node.Node`` behind the same replica
    surface the router speaks (served_height / wait_height / attach).
    Sessions attach through the node's FanoutHub — per-subscriber
    elastic queues, real-socket shape — and the routed session tracks
    delivered heights by parsing frames (router.py)."""

    def __init__(self, node, name: Optional[str] = None):
        self.node = node
        self.name = name or getattr(
            node.config.base, "moniker", ""
        ) or "node"
        self.alive = True
        self.stalled = False
        self.draining = False
        self.on_death: Optional[Callable] = None
        self._subs: Dict[object, object] = {}

    # sessions ride the node's FanoutHub (per-subscriber queues, no
    # on_height signal) — the router parses frame heights on this path
    HUB_DELIVERY = True

    @property
    def role(self) -> str:
        return (
            "validator"
            if getattr(self.node.parts, "privval", None) is not None
            else "follower"
        )

    @property
    def light_plane(self):
        return getattr(self.node, "light_serving_plane", None)

    @property
    def fanout(self):
        return self.node.rpc_server.fanout

    def served_height(self) -> int:
        return self.node.height

    def lag_heights(self) -> int:
        return 0  # a live node's own head IS its committee view

    async def wait_height(self, height: int, timeout_s: float) -> bool:
        deadline = asyncio.get_running_loop().time() + timeout_s
        while self.node.height < height:
            if (
                not self.alive
                or asyncio.get_running_loop().time() >= deadline
            ):
                return False
            await asyncio.sleep(0.01)
        return True

    def attach(self, member) -> None:
        self._subs[member] = self.fanout.attach(
            member, member.query_str, member.query, member.sub_id
        )

    async def detach_member(self, member) -> None:
        sub = self._subs.pop(member, None)
        if sub is not None:
            await self.fanout.detach(sub)

    def members(self) -> int:
        return len(self._subs)

    def status(self) -> dict:
        return {
            "name": self.name,
            "role": self.role,
            "alive": self.alive,
            "stalled": self.stalled,
            "draining": self.draining,
            "served_height": self.served_height(),
            "lag_heights": self.lag_heights(),
            "sessions": len(self._subs),
        }
