"""Serving fleet (ISSUE 19, docs/FLEET.md): non-validator follower
replicas behind the committee + a session router in front of them.

The deployment shape for "millions of users": verification and fan-out
cost concentrate server-side (PAPERS.md), so serving capacity scales
OUT across read replicas — each follower tail-follows the committee
and runs the full read stack (replica fan-out, light serving plane
with an optionally shared process-wide VerifiedHeaderCache, indexer
read barrier) while the SessionRouter owns admission, least-loaded
placement, consistency tokens (height-barrier read-your-writes),
lag-aware shedding and lossless failover.
"""

from .follower import (
    FollowerNode,
    NodeReplica,
    ReplicaFanout,
    StoreSource,
    StreamSource,
    height_events,
)
from .router import (
    FleetOverloadError,
    RoutedSession,
    SessionRouter,
    StaleReadError,
)

__all__ = [
    "FollowerNode",
    "NodeReplica",
    "ReplicaFanout",
    "StoreSource",
    "StreamSource",
    "height_events",
    "FleetOverloadError",
    "RoutedSession",
    "SessionRouter",
    "StaleReadError",
]
