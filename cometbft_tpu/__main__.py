import sys

from .cmd.main import main

sys.exit(main())
