"""In-process node construction + local test networks.

The reference's consensus test fixtures (consensus/common_test.go
randConsensusNet) as a first-class module: build N fully-wired
consensus nodes around local ABCI apps and connect them with in-memory
message delivery — deterministic multi-node consensus on one host, no
sockets. Also the assembly core reused by the real networked node.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .. import types as T
from ..abci.client import AppConns
from ..config import Config, ConsensusConfig
from ..config.config import test_config
from ..consensus import ConsensusState, Handshaker
from ..crypto.keys import Ed25519PrivKey
from ..mempool import CListMempool
from ..models.kvstore import KVStoreApplication
from ..privval import FilePV
from ..state.execution import BlockExecutor
from ..state.store import Store as StateStore
from ..state.state_types import State
from ..store import BlockStore
from ..trace import NOOP as TRACE_NOOP
from ..trace import Tracer, enable_global
from ..types import events as ev
from ..types.genesis import GenesisDoc
from ..utils import kv


def record_clock_anchor(tracer) -> None:
    """Stamp a monotonic→wall clock anchor on a freshly-built ring.

    The pair (one monotonic_ns and one time_ns read back-to-back)
    lets the cross-node timeline tool (trace/timeline.py) rebase
    rings from different processes onto one wall-clock axis. It lives
    HERE — in node assembly, not in trace/ — because ASY107 bans
    wall-clock reads inside the tracing plane; the anchor rides
    ``tracer.meta`` (authoritative, survives ring laps) plus a
    best-effort ``clock.anchor`` instant for raw-event consumers.
    Idempotent per tracer."""
    if not getattr(tracer, "enabled", False) or tracer.meta.get(
        "anchor_mono_ns"
    ):
        return
    mono = time.monotonic_ns()
    wall = time.time_ns()
    tracer.meta["anchor_mono_ns"] = mono
    tracer.meta["anchor_wall_ns"] = wall
    tracer.instant_at("clock.anchor", mono, tid="main", wall_ns=wall)


@dataclass
class NodeParts:
    """Everything a running node is made of (pre-networking)."""

    config: Config
    genesis: GenesisDoc
    privval: Optional[FilePV]
    app: object
    proxy: AppConns
    block_db: kv.KV
    state_db: kv.KV
    block_store: BlockStore
    state_store: StateStore
    state: State
    mempool: CListMempool
    event_bus: ev.EventBus
    block_exec: BlockExecutor
    cs: ConsensusState
    evpool: object = None
    tx_indexer: object = None
    block_indexer: object = None
    index_db: object = None
    # per-height batched indexing drain (state/indexer.py, ISSUE 15);
    # retained so Node.start can upgrade it to async + crash replay
    # and Node._shutdown can flush it bounded
    indexer_service: object = None
    # per-node tracing plane (trace/, docs/TRACE.md); NOOP when
    # [instrumentation] trace_enabled = false
    tracer: object = TRACE_NOOP
    # storage lifecycle plane (store/retention.py, ISSUE 17): always
    # constructed, a no-op until any [storage] retention/snapshot
    # knob is set; Node.start spawns its reconcile loop
    retention: object = None
    # on-disk chunked snapshots (statesync/snapshots.py); None when
    # snapshot generation is off
    snapshot_store: object = None

    def close_stores(self) -> None:
        """Release every store handle (the native logdb backend holds
        an exclusive flock; sqlite keeps fds). Idempotent."""
        for db in (self.index_db, self.block_db, self.state_db):
            if db is not None:
                try:
                    db.close()
                except Exception:
                    pass
        if hasattr(self.tx_indexer, "close"):
            try:
                self.tx_indexer.close()
            except Exception:
                pass


def build_node(
    genesis: GenesisDoc,
    privval: Optional[FilePV],
    app=None,
    config: Optional[Config] = None,
    home: Optional[str] = None,
    wal: bool = False,
) -> NodeParts:
    config = config or test_config(home or ".")
    if config.instrumentation.sanitizer:
        # runtime concurrency sanitizer (docs/LINT.md "Runtime
        # sanitizer"): MUST enable before any plane below constructs
        # its locks — wrapping is a construction-time decision, which
        # is what makes disabled mode free. Per-process, like the
        # lock-order graph it feeds.
        from ..analysis import runtime as _sanitizer

        _sanitizer.enable()
    # the native wirecodec's one-time g++ build runs on a daemon
    # thread NOW so no event loop ever pays it (ASY114 found the
    # subprocess.run reachable from reactor hot paths; module() falls
    # back to the portable codec while the build is in flight)
    from ..state import native_finalize as _native_finalize
    from ..utils import wirecodec as _wirecodec

    _wirecodec.prewarm()
    # same discipline for the native finalize lane (one GIL-releasing
    # hash/encode pass per block, state/native_finalize.py)
    _native_finalize.prewarm()
    # tracing plane: one ring per node; cross-node planes (the crypto
    # worker pool) land on the process-wide tracer, enabled the first
    # time any tracing node is built
    tracer = TRACE_NOOP
    if config.instrumentation.trace_enabled:
        tracer = Tracer(
            name=config.base.moniker or "node",
            size=config.instrumentation.trace_ring_size,
        )
        record_clock_anchor(tracer)
        record_clock_anchor(enable_global())
    if config.crypto.batch_backend:
        # operator-selected verifier backend (config.toml [crypto]
        # batch_backend); empty inherits the process-wide default so
        # embedders/tests that call set_default_backend keep control
        from ..crypto import batch as crypto_batch

        crypto_batch.set_default_backend(config.crypto.batch_backend)
    # node-side snapshot persistence (statesync/snapshots.py): built
    # whenever snapshot generation is on so a locally-constructed
    # kvstore can write straight through the disk seam (an injected
    # app is covered by the retention plane's ABCI mirror instead)
    snapshot_store = None
    if config.storage.snapshot_interval > 0:
        from ..statesync.snapshots import SnapshotStore

        snapshot_store = SnapshotStore(
            os.path.join(home, "snapshots")
            if home
            else tempfile.mkdtemp(prefix="snapshots_"),
            keep_recent=config.storage.snapshot_keep_recent,
        )
    proxy_addr = getattr(config.base, "proxy_app", "")
    if app is None and proxy_addr:
        # out-of-process app (reference proxy_app + abci transport
        # config, node/setup.go:119 createAndStartProxyAppConns)
        from ..abci.socket_client import connect_app_conns

        transport = (
            "grpc" if config.base.abci == "grpc" else "socket"
        )
        proxy = connect_app_conns(proxy_addr, transport)
        app = None
    else:
        if app is None:
            # a pruned node cannot handshake-replay from block 1 —
            # replay_blocks walks app_height+1..store_height and blocks
            # below the retention base are GONE. With the lifecycle
            # knobs on, the default app must persist its committed
            # height so a restart replays only the retained tail
            # (reference PersistentKVStoreApplication).
            s = config.storage
            lifecycle_on = bool(
                s.retain_blocks
                or s.retain_states
                or s.retain_index
                or s.snapshot_interval
            )
            app = KVStoreApplication(
                persist_path=os.path.join(home, "app_state.json")
                if home and lifecycle_on
                else None,
                snapshot_store=snapshot_store,
            )
        elif (
            snapshot_store is not None
            and getattr(app, "snapshot_store", False) is None
        ):
            # an injected kvstore-style app with the seam unset gets
            # the node's store (tests pass retain_height-knobbed apps)
            app.snapshot_store = snapshot_store
        proxy = AppConns.local(app)
    block_db = kv.open_kv(
        config.base.db_backend,
        None
        if config.base.db_backend == "memdb"
        else os.path.join(home, "blockstore.db"),
    )
    state_db = kv.open_kv(
        config.base.db_backend,
        None
        if config.base.db_backend == "memdb"
        else os.path.join(home, "state.db"),
    )
    block_store = BlockStore(block_db)
    state_store = StateStore(state_db)

    state = state_store.load()
    if state is None:
        state = genesis.make_genesis_state()
        state_store.save(state)

    # ABCI handshake: InitChain at genesis / replay stored blocks
    hs = Handshaker(state_store, state, block_store, genesis)
    state = hs.handshake(proxy)

    event_bus = ev.EventBus()
    from ..evidence.pool import EvidencePool
    from ..state.indexer import BlockIndexer, IndexerService, TxIndexer

    evpool = EvidencePool(kv.MemKV(), state_store, block_store)
    # indexing is config-gated (reference [tx_index] indexer = "kv" |
    # "null"); the service accumulates a height's events in-memory on
    # the bus and flushes ONE write_batch per height — off the commit
    # path entirely once Node.start upgrades it to the async drain
    # (state/indexer.py, ISSUE 15). "null" keeps even the
    # accumulation off the publish path.
    tx_indexer = block_indexer = index_db = indexer_service = None
    if config.tx_index.indexer == "kv":
        index_db = kv.open_kv(
            config.base.db_backend,
            None
            if config.base.db_backend == "memdb"
            else os.path.join(home, "tx_index.db"),
        )
        tx_indexer = TxIndexer(index_db)
        block_indexer = BlockIndexer(index_db)
        indexer_service = IndexerService(
            tx_indexer, block_indexer, event_bus
        )
        indexer_service.tracer = tracer
        indexer_service.start()
    elif config.tx_index.indexer == "psql":
        # write-only relational sink (reference state/indexer/sink/psql);
        # retained on the parts so Node.stop can flush + close it
        from ..state.psql_sink import PsqlSink

        sink = PsqlSink(config.tx_index.psql_conn, genesis.chain_id)
        indexer_service = IndexerService(sink, sink, event_bus)
        indexer_service.tracer = tracer
        indexer_service.start()
        tx_indexer = block_indexer = sink
    # mempool flavor by config: clist | app (fork) | nop (ADR-111)
    if config.mempool.type_ == "app":
        from ..mempool.mempool import AppMempool

        mempool = AppMempool(proxy.mempool)
    elif config.mempool.type_ == "nop":
        from ..mempool.mempool import NopMempool

        mempool = NopMempool()
    else:
        mempool = CListMempool(
            proxy.mempool,
            cache_size=config.mempool.cache_size,
            max_tx_bytes=config.mempool.max_tx_bytes,
            max_txs=config.mempool.size,
            recheck=config.mempool.recheck,
            async_recheck=config.mempool.async_recheck,
        )
    block_exec = BlockExecutor(
        state_store,
        proxy.consensus,
        mempool,
        evidence_pool=evpool,
        event_bus=event_bus,
        block_store=block_store,
        block_time_tolerance_ns=config.consensus.block_time_tolerance_ns,
    )
    wal_path = None
    if wal:
        wal_path = os.path.join(
            home or tempfile.mkdtemp(), "cs.wal"
        )
    cs = ConsensusState(
        config.consensus,
        state,
        block_exec,
        block_store,
        mempool,
        priv_validator=privval,
        event_bus=event_bus,
        wal_path=wal_path,
        evidence_pool=evpool,
    )
    cs.tracer = tracer
    mempool.tracer = tracer
    # storage lifecycle plane (store/retention.py): reconciles the
    # [storage] retention window with the app's retain_height and
    # owns ALL pruning once enabled — the executor's legacy inline
    # prune hands off through the hook (state/execution.py _prune)
    from ..store.retention import RetentionPlane

    retention = RetentionPlane(
        config.storage,
        block_store,
        state_store,
        tx_indexer=tx_indexer,
        block_indexer=block_indexer,
        evpool=evpool,
        snapshot_store=snapshot_store,
        proxy=proxy,
        wal_path=wal_path,
        home=home,
        tracer=tracer,
    )
    if retention.enabled:
        block_exec.retention_hook = retention.notify_retain_height
    return NodeParts(
        config=config,
        genesis=genesis,
        privval=privval,
        app=app,
        proxy=proxy,
        block_db=block_db,
        state_db=state_db,
        block_store=block_store,
        state_store=state_store,
        state=state,
        mempool=mempool,
        event_bus=event_bus,
        block_exec=block_exec,
        cs=cs,
        evpool=evpool,
        tx_indexer=tx_indexer,
        block_indexer=block_indexer,
        index_db=index_db,
        indexer_service=indexer_service,
        tracer=tracer,
        retention=retention,
        snapshot_store=snapshot_store,
    )


def make_genesis(
    n_validators: int,
    chain_id: str = "test-chain",
    power: int = 10,
    genesis_time_ns: int = 0,
):
    """Returns (GenesisDoc, [FilePV-like in-memory signers]).

    Genesis is backdated 1h by default so chains generated forward from
    it (1s per block) stay in the past for wall-clock checks (block-time
    tolerance, light-client drift)."""
    privs = [Ed25519PrivKey.generate() for _ in range(n_validators)]
    vals = [T.Validator(p.pub_key(), power) for p in privs]
    gen = GenesisDoc(
        chain_id=chain_id,
        validators=vals,
        genesis_time_ns=genesis_time_ns or time.time_ns() - 3_600_000_000_000,
    )
    pvs = []
    for p in privs:
        d = tempfile.mkdtemp(prefix="pv_")
        pv = FilePV(
            p, os.path.join(d, "key.json"), os.path.join(d, "state.json")
        )
        pv.save_key()
        pv.save_state()
        pvs.append(pv)
    # order pvs to match sorted validator order for convenience
    vs = gen.validator_set()
    order = {v.address: i for i, v in enumerate(vs.validators)}
    pvs.sort(key=lambda pv: order[pv.pub_key().address()])
    return gen, pvs


class LocalNet:
    """Fully-connected in-memory delivery between consensus states.

    Delivery is flood-with-dedup plus a CATCH-UP healer (the reactor's
    gossipDataForCatchup analog): a node whose round state trails a
    peer's committed height is periodically re-fed that block + commit
    through the normal commit_block path. The flood alone has no
    retransmission, so any delivery skew (batched vote windows, WAL
    group-commit broadcast deferral, loop contention) could strand a
    node in COMMIT waiting for parts nobody will ever resend — the
    real p2p reactor heals this with per-peer gossip routines, and the
    harness must match that delivery contract."""

    def __init__(
        self,
        nodes: List[NodeParts],
        drop: Optional[Callable] = None,
        heal_interval_s: float = 0.05,
    ):
        self.nodes = nodes
        self.drop = drop  # (src_idx, dst_idx, kind, payload) -> bool
        self.heal_interval_s = heal_interval_s
        self._healer: Optional[asyncio.Task] = None
        for i, n in enumerate(nodes):
            n.cs.add_broadcast_hook(self._make_hook(i))

    def _make_hook(self, src: int):
        def hook(kind, payload):
            for j, other in enumerate(self.nodes):
                if j == src:
                    continue
                if self.drop and self.drop(src, j, kind, payload):
                    continue
                try:
                    other.cs.enqueue_nowait(kind, payload, f"node{src}")
                except asyncio.QueueFull:
                    pass

        return hook

    async def start(self):
        for n in self.nodes:
            await n.cs.start()
        if self.heal_interval_s > 0 and len(self.nodes) > 1:
            self._healer = asyncio.create_task(self._heal_loop())

    async def _heal_loop(self):
        """Re-feed committed blocks to lagging nodes (reference
        consensus/reactor.go gossipDataForCatchup, harness-sized)."""
        import traceback

        from ..consensus.reactor import CommitBlockMessage

        while True:
            await asyncio.sleep(self.heal_interval_s)
            try:
                stores = [n.block_store.height() for n in self.nodes]
                for j, n in enumerate(self.nodes):
                    h = n.cs.rs.height
                    for i, m in enumerate(self.nodes):
                        if i == j or stores[i] < h:
                            continue
                        if self.drop and self.drop(
                            i, j, "commit_block", None
                        ):
                            continue
                        block = m.block_store.load_block(h)
                        commit = m.block_store.load_seen_commit(
                            h
                        ) or m.block_store.load_block_commit(h)
                        if block is None or commit is None:
                            continue
                        try:
                            n.cs.enqueue_nowait(
                                "commit_block",
                                CommitBlockMessage(
                                    block,
                                    commit,
                                    m.block_store.load_extended_commit(h),
                                ),
                                f"node{i}",
                            )
                        except asyncio.QueueFull:
                            pass
                        break
            except asyncio.CancelledError:
                raise
            except Exception:
                traceback.print_exc()

    async def stop(self):
        if self._healer is not None:
            self._healer.cancel()
            self._healer = None
        for n in self.nodes:
            # bounded (ASY110): one wedged state machine must not
            # hang the whole test net's teardown
            try:
                await asyncio.wait_for(n.cs.stop(), 15.0)
            except asyncio.TimeoutError:
                pass

    async def wait_for_height(self, height: int, timeout: float = 30.0):
        async def waiter():
            while True:
                if all(
                    n.block_store.height() >= height for n in self.nodes
                ):
                    return
                await asyncio.sleep(0.02)

        await asyncio.wait_for(waiter(), timeout)
