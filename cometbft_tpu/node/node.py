"""Full node assembly: transport + switch + reactors + consensus
(reference node/node.go:285 NewNode, :616 OnStart, node/setup.go).

Startup phases mirror the reference: (statesync ->) blocksync ->
consensus. When blocksync is enabled the consensus state machine is
built but NOT started; once the pool reports caught-up the node
switches to consensus (reference consensus/reactor.go:121
SwitchToConsensus). With the fork's AdaptiveSync, blocksync pipelines
verified blocks straight into the RUNNING consensus state machine
instead (reference blocksync/reactor_adaptive.go)."""

from __future__ import annotations

import asyncio
import os
import traceback
from typing import Optional

from ..blocksync.net_reactor import BlockSyncNetReactor
from ..config import Config
from ..consensus.reactor import ConsensusReactor
from ..evidence.reactor import EvidenceReactor
from ..mempool.reactor import MempoolReactor
from ..p2p import MemoryTransport, NodeInfo, NodeKey, Switch, TCPTransport
from ..types.genesis import GenesisDoc
from ..utils.log import get_logger
from ..utils.tasks import spawn
from .inprocess import NodeParts, build_node

_log = get_logger("node")


def _strip_proto(addr: str) -> str:
    for p in ("tcp://", "unix://"):
        if addr.startswith(p):
            return addr[len(p):]
    return addr


class Node:
    """A running full node / validator."""

    def __init__(
        self,
        config: Config,
        genesis: GenesisDoc,
        privval=None,
        app=None,
        node_key: Optional[NodeKey] = None,
        transport: Optional[object] = None,
        home: Optional[str] = None,
    ):
        self.config = config
        self.genesis = genesis
        self.parts: NodeParts = build_node(
            genesis, privval, app=app, config=config, home=home,
            wal=bool(home),
        )
        self.node_key = node_key or NodeKey.generate()
        self.node_info = NodeInfo(
            node_id=self.node_key.node_id,
            network=genesis.chain_id,
            moniker=config.base.moniker,
        )
        # e2e upgrade perturbation: a restarted process can present a
        # bumped software version (the single-binary analog of the
        # reference's docker-image swap, test/e2e/runner/perturb.go:37)
        _v = os.environ.get("CMT_NODE_VERSION")
        if _v:
            self.node_info.version = _v
        if transport is None:
            # fault injection by config (reference FuzzConnConfig);
            # maybe_fuzz treats disabled/None as passthrough
            transport = TCPTransport(
                self.node_key,
                self.node_info,
                fuzz_config=getattr(config, "fuzz", None),
            )
        self.transport = transport
        # self-healing connectivity plane knobs ([p2p], README table)
        reconnect_config = {
            "base_s": config.p2p.reconnect_base_s,
            "cap_s": config.p2p.reconnect_cap_s,
            "fast_attempts": config.p2p.reconnect_fast_attempts,
            "slow_interval_s": config.p2p.reconnect_slow_interval_s,
            "starvation_s": config.p2p.starvation_s,
        }
        if config.p2p.use_libp2p_equivalent:
            # fork feature: alternative stream-multiplexed switcher
            # (reference lp2p selection at node/node.go:476-575)
            from ..lp2p import Lp2pSwitch

            self.switch = Lp2pSwitch(
                self.transport,
                self.node_info,
                send_rate=config.p2p.send_rate,
                recv_rate=config.p2p.recv_rate,
                use_autopool=config.p2p.use_autopool,
                reconnect_config=reconnect_config,
            )
        else:
            self.switch = Switch(
                self.transport,
                self.node_info,
                mconn_config={
                    "send_rate": config.p2p.send_rate,
                    "recv_rate": config.p2p.recv_rate,
                    "flush_throttle_s": config.p2p.flush_throttle_ms / 1000.0,
                },
                use_autopool=config.p2p.use_autopool,
                reconnect_config=reconnect_config,
            )
        self.switch.min_peers = config.p2p.min_peers

        blocksync_active = config.blocksync.enable and not config.statesync.enable
        adaptive = config.blocksync.adaptive_sync
        # consensus gossip stays off until every sync phase completes
        # (statesync hand-off re-enables blocksync, which re-enables us)
        sync_pending = config.statesync.enable or (
            blocksync_active and not adaptive
        )
        self.consensus_reactor = ConsensusReactor(
            self.parts.cs,
            self.parts.block_store,
            wait_sync=sync_pending,
        )
        if config.mempool.type_ == "app":
            from ..mempool.reactor import AppMempoolReactor

            self.mempool_reactor = AppMempoolReactor(
                self.parts.mempool, broadcast=config.mempool.broadcast
            )
        else:
            self.mempool_reactor = MempoolReactor(
                self.parts.mempool,
                broadcast=config.mempool.broadcast,
                batch_max_txs=config.mempool.batch_max_txs,
                batch_flush_ms=config.mempool.batch_flush_ms,
            )
        self.evidence_reactor = EvidenceReactor(self.parts.evpool)
        self.blocksync_reactor = BlockSyncNetReactor(
            self.parts.state,
            self.parts.block_exec,
            self.parts.block_store,
            on_caught_up=self._on_caught_up,
            block_ingestor=self.parts.cs if adaptive else None,
            active=blocksync_active,
            local_blocks_chain=self._local_blocks_chain,
        )
        from ..p2p.pex import AddrBook, PexReactor
        from ..statesync.reactor import StateSyncReactor

        self.statesync_reactor = StateSyncReactor(
            self.parts.proxy, enabled=config.statesync.enable
        )
        # serve-floor handle (store/retention.py): chunks being
        # streamed to a joiner pin their height against pruning
        self.statesync_reactor.retention = self.parts.retention
        self.addr_book = AddrBook(
            os.path.join(home, "addrbook.json") if home else None,
            our_id=self.node_key.node_id,
        )
        for seed in (config.p2p.seeds or "").split(","):
            if seed.strip():
                self.addr_book.add_address(seed.strip())
        # the reconnect plane consults the book for re-learned
        # addresses and records dial/conn failures into it
        self.switch.addr_book = self.addr_book
        self.pex_reactor = (
            PexReactor(
                self.addr_book,
                seed_mode=config.p2p.seed_mode,
                target_outbound=config.p2p.max_num_outbound_peers,
            )
            if config.p2p.pex
            else None
        )
        self.switch.add_reactor("consensus", self.consensus_reactor)
        self.switch.add_reactor("mempool", self.mempool_reactor)
        self.switch.add_reactor("evidence", self.evidence_reactor)
        self.switch.add_reactor("blocksync", self.blocksync_reactor)
        self.switch.add_reactor("statesync", self.statesync_reactor)
        if self.pex_reactor is not None:
            self.switch.add_reactor("pex", self.pex_reactor)
        # tracing plane: point the networked planes at the node ring
        # (consensus/mempool/WAL got theirs in build_node)
        self.switch.tracer = self.parts.tracer
        self.blocksync_reactor.inner.tracer = self.parts.tracer
        # cross-node causal tracing (docs/TRACE.md): stamp outbound
        # consensus/mempool/blocksync messages with a trace context so
        # peers record correlated receive instants. Origin is the
        # moniker (matches the ring label chaos dumps use).
        # trace_msg_stamp gates only the OUTBOUND stamp — a node with
        # it off still records arrivals from stamping peers.
        if self.parts.tracer.enabled:
            self.switch.enable_stamping(
                self.parts.tracer,
                config.base.moniker or self.node_key.node_id[:8],
                outbound=config.instrumentation.trace_msg_stamp,
            )
        self._adaptive = adaptive
        self._cs_started = False
        self.rpc_server = None
        self.grpc_server = None
        self.rpc_env = None
        self._statesync_task = None
        self.statesync_error = None
        # cross-client verified-header cache (light/serving.py):
        # injectable so a co-resident serving plane and this node's
        # statesync restore share verification work; lazily created
        # by _statesync_routine otherwise
        self.light_header_cache = None
        self.metrics = None
        self.metrics_server = None
        self.debug_server = None
        self.watchdog = None
        # last _shutdown's ShutdownGuard (stalled-stage flight
        # records for reports/tests); set when stop()/kill() runs
        self.shutdown_guard = None
        # runtime health plane (cometbft_tpu/obs, docs/OBS.md): the
        # loop watchdog object is built here (started in start() — it
        # needs the running loop) so Environment.from_node and the
        # metrics attach can hold a stable reference
        from ..obs import LoopWatchdog, QueueRegistry

        inst = config.instrumentation
        self.loop_watchdog = (
            LoopWatchdog(
                tracer=self.parts.tracer,
                interval_s=inst.loop_lag_interval_ms / 1e3,
                stall_s=inst.loop_stall_ms / 1e3,
                name=config.base.moniker or "node",
            )
            if inst.loop_watchdog
            else None
        )
        self.queues = QueueRegistry()
        self._register_queues()

    def _register_queues(self) -> None:
        """Point the backpressure registry (obs/queues.py) at every
        bounded queue in the hot planes. Entries are callables read at
        scrape time — planes rebuild queues across start/stop."""
        q = self.queues
        mr = self.mempool_reactor
        ing = getattr(mr, "ingest", None)
        if ing is not None:
            q.register("mempool.ingest", ing.queue_stats)
        q.register(
            "consensus.inbox",
            lambda: self.parts.cs.queue.stats()
            if getattr(self.parts.cs.queue, "stats", None)
            else None,
        )
        q.register("events.subs", self.parts.event_bus.queue_stats)
        # outbound fan-out plane (rpc/fanout.py): per-websocket-
        # subscriber frame queues, aggregated; None until the RPC
        # server exists
        q.register(
            "rpc.fanout",
            lambda: self.rpc_server.fanout.queue_stats()
            if getattr(self, "rpc_server", None) is not None
            else None,
        )
        # per-height batched index drain (state/indexer.py)
        q.register(
            "state.index",
            lambda: self.parts.indexer_service.queue_stats()
            if self.parts.indexer_service is not None
            else None,
        )
        # storage lifecycle plane (store/retention.py): base heights,
        # pruned totals, snapshot + disk-bytes stats
        q.register(
            "store.retention",
            lambda: self.parts.retention.stats()
            if self.parts.retention is not None
            and self.parts.retention.enabled
            else None,
        )

        def p2p_send():
            depth = hwm = dropped = enqueued = 0
            seen = False
            for peer in list(self.switch.peers.values()):
                mc = getattr(peer, "mconn", None)
                if mc is None or not hasattr(mc, "send_queue_stats"):
                    continue
                seen = True
                s = mc.send_queue_stats()
                depth += s["depth"]
                hwm = max(hwm, s["high_watermark"])
                dropped += s["dropped"]
                enqueued += s["enqueued"]
            if not seen:
                return None
            return {
                "depth": depth,
                "high_watermark": hwm,
                "dropped": dropped,
                "enqueued": enqueued,
            }

        q.register("p2p.send", p2p_send)
        q.register(
            "blocksync.window",
            lambda: self.blocksync_reactor.inner.pool.queue_stats()
            if getattr(self.blocksync_reactor.inner, "pool", None)
            is not None
            else None,
        )
        # process-wide: the parallel-verify dispatch plane (shared by
        # every in-process node; reported per node for convenience)
        from ..crypto.parallel_verify import dispatch_stats_if_running

        q.register("crypto.verify.dispatch", dispatch_stats_if_running)
        # process-wide: the unified verify scheduler's per-class
        # queue-depth gauges (live/light/catchup lanes pending)
        from ..crypto.scheduler import sched_stats_if_running

        q.register("crypto.sched", sched_stats_if_running)

    # --- phase switching ----------------------------------------------

    def _local_blocks_chain(self, state) -> bool:
        """True when our own validator holds >=1/3 voting power, so
        blocksync cannot progress without our votes (reference
        blocksync/reactor.go:448 localNodeBlocksTheChain)."""
        pv = self.parts.privval
        if pv is None:
            return False
        try:
            _, val = state.validators.get_by_address(pv.pub_key().address())
        except Exception:
            return False
        if val is None:
            return False
        return val.voting_power >= state.validators.total_voting_power() / 3

    async def _statesync_routine(self) -> None:
        """Phase 1: snapshot-restore, then hand off to blocksync
        (reference node/setup.go:560 performStateSync)."""
        from ..statesync.stateprovider import LightClientStateProvider

        cfg = self.config.statesync
        try:
            # the restore shares verification work with any light
            # serving plane in this process (light/serving.py): an
            # injected node.light_header_cache wins; otherwise the
            # node gets its own (a retried sync then re-pays
            # nothing). Sharing contract guard: with a SINGLE rpc
            # server the restore client has zero witnesses, so its
            # cross-check is vacuous — what the sole (untrusted)
            # primary serves must then only ever reach a cache
            # PRIVATE to this restore, never process-shared state a
            # serving plane would hand to every session
            from ..light.serving import VerifiedHeaderCache

            if len(cfg.rpc_servers) >= 2:
                header_cache = self.light_header_cache
                if header_cache is None:
                    header_cache = VerifiedHeaderCache(
                        self.genesis.chain_id
                    )
                    self.light_header_cache = header_cache
            else:
                header_cache = VerifiedHeaderCache(
                    self.genesis.chain_id
                )
            # constructor light-verifies the trust root (blocking
            # HTTP) — keep it off this event loop
            provider = await asyncio.to_thread(
                LightClientStateProvider,
                self.genesis.chain_id,
                cfg.rpc_servers,
                cfg.trust_height,
                bytes.fromhex(cfg.trust_hash)
                if isinstance(cfg.trust_hash, str)
                else cfg.trust_hash,
                int(cfg.trust_period_s * 1e9),
                genesis=self.genesis,
                header_cache=header_cache,
            )
            try:
                state = await self.statesync_reactor.sync(
                    provider,
                    self.parts.state_store,
                    self.parts.block_store,
                    discovery_time_s=cfg.discovery_time_s,
                )
            finally:
                provider.close()
            self.parts.state = state
            _log.info(
                "statesync complete, switching to blocksync",
                height=state.last_block_height,
                adaptive=self._adaptive,
            )
            if self._adaptive:
                # adaptive: consensus runs DURING blocksync and is the
                # block ingestor — align it with the synced state first
                self.parts.cs.update_to_state(state)
                await self.parts.cs.start()
                self._cs_started = True
                self.consensus_reactor.switch_to_consensus()
            await self.blocksync_reactor.activate(state)
        except asyncio.CancelledError:
            raise  # node stop cancels the statesync task
        except Exception as e:
            # statesync failure is fatal (reference node/setup.go
            # performStateSync): a node that can't bootstrap must not
            # linger half-alive
            self.statesync_error = e
            traceback.print_exc()
            _log.error("statesync failed, stopping node", err=repr(e))
            spawn(self.stop(), name="node-stop")

    def _on_caught_up(self, state) -> None:
        spawn(self._switch_to_consensus(state), name="switch-to-consensus")

    async def _switch_to_consensus(self, state) -> None:
        _log.info(
            "switching to consensus", height=state.last_block_height
        )
        if self._cs_started:
            self.consensus_reactor.switch_to_consensus()
            return
        try:
            self.parts.cs.update_to_state(state)
            await self.parts.cs.start()
            self._cs_started = True
            self.consensus_reactor.switch_to_consensus()
        except asyncio.CancelledError:
            raise  # node stop cancels the handoff task
        except Exception:
            traceback.print_exc()

    # --- lifecycle ----------------------------------------------------

    @property
    def listen_addr(self) -> str:
        return self.transport.listen_addr

    async def start(self) -> None:
        await self.transport.listen(_strip_proto(self.config.p2p.laddr))
        await self.switch.start()
        _log.info(
            "node started",
            node_id=self.node_info.node_id[:12],
            laddr=self.listen_addr,
            chain=self.genesis.chain_id,
            height=self.parts.block_store.height(),
        )
        if self.parts.indexer_service is not None:
            # per-height batched indexing (state/indexer.py): replay
            # any crash gap past the idx:last marker, then flush from
            # the bounded async drain instead of inline at seal time
            await self.parts.indexer_service.start_async(
                self.parts.block_store, self.parts.state_store
            )
        if self.parts.retention is not None:
            # storage lifecycle plane (store/retention.py): no-op
            # unless a [storage] retention/snapshot knob is set
            await self.parts.retention.start()
        rpc_env = None
        if self.config.rpc.laddr:
            from ..rpc import Environment, RPCServer

            rpc_env = Environment.from_node(self)
            self.rpc_server = RPCServer(rpc_env)
            await self.rpc_server.start(_strip_proto(self.config.rpc.laddr))
        if self.config.rpc.grpc_laddr:
            # legacy gRPC broadcast API (reference rpc/grpc) — serves
            # even when the JSON-RPC listener is disabled; shares the
            # env's CommitWaiterMap with the JSON-RPC route
            from ..rpc import Environment
            from ..rpc.grpc_api import GRPCBroadcastServer

            rpc_env = rpc_env or Environment.from_node(self)
            self.grpc_server = GRPCBroadcastServer(
                rpc_env,
                _strip_proto(self.config.rpc.grpc_laddr),
                asyncio.get_running_loop(),
                timeout_s=self.config.rpc.timeout_broadcast_tx_commit_s,
            )
            self.grpc_server.start()
        # retained so _shutdown can release the commit-waiter drain
        self.rpc_env = rpc_env
        if self.config.instrumentation.prometheus:
            from ..utils.metrics import MetricsServer, NodeMetrics

            self.metrics = NodeMetrics(self.genesis.chain_id)
            self.metrics.attach(self)
            self.metrics_server = MetricsServer(self.metrics)
            await self.metrics_server.start(
                _strip_proto(
                    self.config.instrumentation.prometheus_listen_addr
                )
            )
        if self.config.instrumentation.pprof_laddr:
            # reference node/node.go:624-627: profiling listener by config
            from ..utils.debug import DebugServer

            self.debug_server = DebugServer(
                self.config.instrumentation.pprof_laddr
            )
            await self.debug_server.start()
        if self.loop_watchdog is not None:
            # loop-lag heartbeat + stall flight recorder (docs/OBS.md)
            self.loop_watchdog.start()
        if self.config.instrumentation.watchdog_stall_s > 0:
            from ..utils.debug import StuckTaskWatchdog

            self.watchdog = StuckTaskWatchdog(
                interval_s=min(
                    5.0, self.config.instrumentation.watchdog_stall_s / 2
                ),
                stall_s=self.config.instrumentation.watchdog_stall_s,
            )
            self.watchdog.start()
        # consensus starts now unless a sync phase must complete first
        if self.config.statesync.enable:
            self._statesync_task = asyncio.create_task(
                self._statesync_routine()
            )
        elif not self.blocksync_reactor.active or self._adaptive:
            await self.parts.cs.start()
            self._cs_started = True
        if self.config.p2p.persistent_peers:
            self.switch.dial_peers_async(
                [
                    a.strip()
                    for a in self.config.p2p.persistent_peers.split(",")
                    if a.strip()
                ],
                persistent=True,
            )

    async def kill(self) -> None:
        """Simulated process crash for in-process chaos tests: tear
        every task down abruptly — consensus abandons its WAL without
        flushing (ConsensusState.crash), nothing performs a graceful
        handoff — then release store handles so a restarted Node on
        the same home recovers exclusively through WAL replay + ABCI
        handshake replay (consensus/replay.py), the same path a real
        power cut exercises via utils/fail.py."""
        await self._shutdown(graceful=False)

    async def stop(self) -> None:
        await self._shutdown(graceful=True)

    async def _shutdown(self, graceful: bool) -> None:
        """Bounded, staged teardown (obs/shutdown.py, docs/OBS.md):
        every await below runs under a per-stage budget with
        stop→cancel→abandon escalation, so one wedged sub-plane can
        never hang the whole stop path — the breach is flight-recorded
        into the trace ring and the remaining stages (store-handle
        release above all) still run."""
        from ..obs import ShutdownGuard

        guard = ShutdownGuard(
            tracer=self.parts.tracer,
            name=self.config.base.moniker or "node",
            budget_s=self.config.instrumentation.shutdown_stage_budget_s,
        )
        self.shutdown_guard = guard
        if getattr(self, "watchdog", None) is not None:
            self.watchdog.stop()
        if getattr(self, "loop_watchdog", None) is not None:
            self.loop_watchdog.stop()
        if self._statesync_task is not None:
            self._statesync_task.cancel()
        # kill(): servers still close (an in-process restart must be
        # able to rebind, and dead stores must stop being served) —
        # the crash/graceful split is consensus' WAL handling only
        if self.metrics_server is not None:
            await guard.stage("metrics", self.metrics_server.stop())
        if self.debug_server is not None:
            await guard.stage("debug", self.debug_server.stop())
        if self.grpc_server is not None:
            self.grpc_server.stop()
        if self.rpc_server is not None:
            await guard.stage("rpc", self.rpc_server.stop())
        if getattr(self, "rpc_env", None) is not None:
            # commit-waiter drain (rpc/fanout.py): after both servers
            # so no route can re-create it mid-teardown
            await guard.stage("rpc_env", self.rpc_env.close())
        if self._cs_started:
            await guard.stage(
                "consensus",
                self.parts.cs.stop() if graceful
                else self.parts.cs.crash(),
            )
        # the switch stage gets 3x: it contains per-plane bounded
        # stops of its own (reactor stops 5-10s each under the ASY110
        # bounds) which must get a chance to run before escalation
        ok = await guard.stage(
            "switch", self.switch.stop(), budget_s=guard.budget_s * 3
        )
        if not ok and hasattr(self.switch, "abort"):
            # escalation floor: an abandoned graceful stop must STILL
            # kill every conn fd synchronously — a zombie conn makes
            # remotes dup-discard this node's next incarnation's dials
            # (it could never rejoin the net)
            try:
                self.switch.abort()
            except Exception:
                traceback.print_exc()
        if self.parts.indexer_service is not None:
            # after consensus/switch: nothing publishes anymore, so
            # stop() can flush the remaining sealed heights bounded
            await guard.stage(
                "indexer", self.parts.indexer_service.stop()
            )
        if self.parts.retention is not None:
            # before the stores close: a reconcile pass mid-flight in
            # its worker thread must finish (or be abandoned bounded)
            # while its dbs are still open
            await guard.stage(
                "retention", self.parts.retention.stop()
            )
        # release store handles (psql sink flush+close; logdb flocks;
        # sqlite fds) — a restart in the same process must be able to
        # reopen every database. Last on purpose: it must run even
        # when every stage above was abandoned.
        await guard.stage(
            "stores", asyncio.to_thread(self.parts.close_stores)
        )
        if not guard.clean:
            _log.error(
                "shutdown completed with stalled stages",
                node=self.config.base.moniker,
                stages=[r["stage"] for r in guard.stalls],
                abandoned=guard.abandoned,
            )

    # --- convenience --------------------------------------------------

    async def dial(self, addr: str, persistent: bool = False):
        return await self.switch.dial_peer(addr, persistent=persistent)

    @property
    def height(self) -> int:
        return self.parts.block_store.height()

    def block_id_hash_at(self, height: int) -> Optional[bytes]:
        """Committed block ID hash at a height, or None — the
        commit-introspection surface the chaos invariant checkers
        compare across nodes (chaos/invariants.py)."""
        meta = self.parts.block_store.load_block_meta(height)
        return None if meta is None else bytes(meta.block_id.hash)
