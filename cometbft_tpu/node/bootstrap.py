"""Offline state bootstrap (reference node.BootstrapState,
node/node.go:161-280).

Populates an (empty) node home with light-client-verified state at a
chosen height so the node can start directly in blocksync from there —
statesync without the snapshot transfer, for operators who restore app
state out-of-band (e.g. from their own backup).
"""

from __future__ import annotations

import os
from typing import Optional


def bootstrap_state(
    config,
    genesis,
    home: str,
    height: Optional[int] = None,
) -> int:
    """Verify state at `height` (default: the statesync trust height)
    via the light client against config.statesync.rpc_servers, and
    persist it into the node's state/block stores. Returns the
    bootstrapped height.

    Refuses to overwrite a store that already has newer state
    (reference node/node.go:189-199)."""
    from ..state.store import Store as StateStore
    from ..statesync.stateprovider import LightClientStateProvider
    from ..store import BlockStore
    from ..utils import kv

    cfg = config.statesync
    if not cfg.rpc_servers:
        raise ValueError(
            "bootstrap-state requires [statesync] rpc_servers"
        )
    height = height or cfg.trust_height
    if height <= 0:
        raise ValueError("bootstrap-state requires a positive height")

    state_db = kv.open_kv(
        config.base.db_backend,
        None
        if config.base.db_backend == "memdb"
        else os.path.join(home, "state.db"),
    )
    block_db = kv.open_kv(
        config.base.db_backend,
        None
        if config.base.db_backend == "memdb"
        else os.path.join(home, "blockstore.db"),
    )
    state_store = StateStore(state_db)
    block_store = BlockStore(block_db)
    existing = state_store.load()
    if existing is not None and existing.last_block_height >= height:
        raise RuntimeError(
            f"state store already at height "
            f"{existing.last_block_height} >= {height}; refusing to "
            "rewind via bootstrap (use rollback)"
        )

    trust_hash = (
        bytes.fromhex(cfg.trust_hash)
        if isinstance(cfg.trust_hash, str)
        else cfg.trust_hash
    )
    provider = LightClientStateProvider(
        genesis.chain_id,
        list(cfg.rpc_servers),
        cfg.trust_height,
        trust_hash,
        int(cfg.trust_period_s * 1e9),
        genesis=genesis,
    )
    try:
        state = provider.state(height)
        commit = provider.commit(height)
    finally:
        provider.close()

    state_store.bootstrap(state)
    # seen commit lets the consensus reactor serve/verify the
    # bootstrapped height and blocksync anchor at height+1
    block_store.save_seen_commit(height, commit)
    return height
