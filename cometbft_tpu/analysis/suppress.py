"""Suppression comments.

Three spellings, all comment-based so they survive formatters:

``# bftlint: disable=ASY101[,JAX201]``
    silences the named rule(s) on this line only.
``# bftlint: disable-next=ASY101``
    silences the named rule(s) on the following line.
``# bftlint: disable-file=ASY101``
    silences the named rule(s) for the whole file (conventionally
    placed near the top).

Rules may be named by id (``ASY101``) or name
(``blocking-call-in-async``); ``all`` matches every rule.  Unknown
rule names in a suppression are themselves reported as findings
(``SUP001``) so typos cannot silently disable nothing.
"""
from __future__ import annotations

import io
import re
import tokenize
from typing import List, NamedTuple, Set, Tuple

from .findings import Finding
from .registry import resolve

_DIRECTIVE = re.compile(
    r"#\s*bftlint:\s*(disable(?:-next|-file)?)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)

ALL = "all"


class Suppressions(NamedTuple):
    # (line, rule_id-or-ALL) pairs; file-wide entries use line 0
    by_line: Set[Tuple[int, str]]
    file_wide: Set[str]
    errors: List[Finding]

    def is_suppressed(self, line: int, rule_id: str) -> bool:
        return (
            rule_id in self.file_wide
            or ALL in self.file_wide
            or (line, rule_id) in self.by_line
            or (line, ALL) in self.by_line
        )


def parse_suppressions(path: str, source: str) -> Suppressions:
    by_line: Set[Tuple[int, str]] = set()
    file_wide: Set[str] = set()
    errors: List[Finding] = []
    for lineno, comment in _comments(source):
        m = _DIRECTIVE.search(comment)
        if m is None:
            continue
        kind, spec = m.group(1), m.group(2)
        for raw in spec.split(","):
            raw = raw.strip()
            rid = ALL if raw == ALL else resolve(raw)
            if rid is None:
                errors.append(
                    Finding(
                        path, lineno, 0, "SUP001", "unknown-suppression",
                        f"suppression names unknown rule {raw!r}",
                    )
                )
                continue
            if kind == "disable":
                by_line.add((lineno, rid))
            elif kind == "disable-next":
                by_line.add((lineno + 1, rid))
            else:  # disable-file
                file_wide.add(rid)
    return Suppressions(by_line, file_wide, errors)


def _comments(source: str):
    """Yield (lineno, text) for every comment token.

    Falls back to a line-regex scan if tokenization fails (the engine
    reports the syntax error separately via ast.parse).
    """
    try:
        for tok in tokenize.generate_tokens(
            io.StringIO(source).readline
        ):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, ln in enumerate(source.splitlines(), 1):
            if "#" in ln:
                yield i, ln[ln.index("#"):]
