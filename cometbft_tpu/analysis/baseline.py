"""Checked-in baseline of pre-existing violations.

The baseline maps ``path -> rule_id -> count``.  Counts, not line
numbers: unrelated edits shift lines constantly, and a count contract
("this file has at most N ASY104s") is stable under reflow while
still ratcheting — any NEW violation pushes the count over and fails
the run, and fixing one makes the entry stale so it gets ratcheted
down rather than quietly becoming headroom.
"""
from __future__ import annotations

import json
from typing import Dict, List, NamedTuple, Tuple

from .findings import Finding

VERSION = 1

BaselineMap = Dict[str, Dict[str, int]]


class StaleEntry(NamedTuple):
    path: str
    rule_id: str
    allowed: int
    current: int

    def render(self) -> str:
        return (
            f"stale baseline: {self.path} {self.rule_id} allows "
            f"{self.allowed} but only {self.current} remain — "
            f"regenerate with --update-baseline"
        )


def load(path: str) -> BaselineMap:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(f"{path}: not a bftlint baseline file")
    return {
        p: dict(rules) for p, rules in data["entries"].items()
    }


def save(path: str, entries: BaselineMap) -> None:
    doc = {
        "version": VERSION,
        "note": (
            "pre-existing bftlint violations; regenerate with "
            "`python -m cometbft_tpu.analysis --update-baseline`"
        ),
        "entries": {
            p: {r: entries[p][r] for r in sorted(entries[p])}
            for p in sorted(entries)
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")


def build(findings: List[Finding]) -> BaselineMap:
    entries: BaselineMap = {}
    for f in findings:
        entries.setdefault(f.path, {}).setdefault(f.rule_id, 0)
        entries[f.path][f.rule_id] += 1
    return entries


def apply(
    findings: List[Finding], baseline: BaselineMap
) -> Tuple[List[Finding], List[StaleEntry]]:
    """Split current findings against the baseline.

    Returns ``(new, stale)``.  A (path, rule) pair whose current count
    exceeds its allowance reports ALL its findings (line numbers can't
    tell old from new); a pair under its allowance is stale.
    """
    current = build(findings)
    new: List[Finding] = []
    for f in findings:
        allowed = baseline.get(f.path, {}).get(f.rule_id, 0)
        got = current[f.path][f.rule_id]
        if got > allowed:
            note = (
                f" ({got} found, baseline allows {allowed})"
                if allowed
                else ""
            )
            new.append(
                Finding(
                    f.path, f.line, f.col, f.rule_id, f.rule_name,
                    f.message + note,
                    chain=f.chain,
                    domain_trace=f.domain_trace,
                )
            )
    stale: List[StaleEntry] = []
    for p, rules in baseline.items():
        for rid, allowed in rules.items():
            got = current.get(p, {}).get(rid, 0)
            if got < allowed:
                stale.append(StaleEntry(p, rid, allowed, got))
    return new, sorted(stale)
