"""Empirical committee-scaling probe: the runtime half of the
complexity plane (docs/LINT.md "Complexity rules").

The static pass (analysis/complexity.py, ASY117/118/119) PROVES a hot
path reaches a committee-domain loop; this module MEASURES the slope.
Each registered site drives one of the flagged (and since fixed) call
paths in-process at committee sizes {4, 16, 64, 128}, fits a log-log
scaling exponent over the median walls, and compares it against the
per-site budget in tools/scaling_budgets.toml. Breaches drain into
chaos runs and the bench ``scaling`` leg exactly like sanitizer
findings: an un-injected breach is a violation; an injected quadratic
site (``inject_quadratic_site``, name-prefixed ``chaos.`` like
inject_lock_inversion's probes) must be DETECTED or the run fails —
a probe that cannot flag its own O(n^2) plant proves nothing.

Real sites (the ASY117/118 fix targets):

- ``vote_add``        — VoteSet.add_vote for a full committee (the
                        memoized total_voting_power fix: unmemoized,
                        every add resummed O(V) powers → slope ~2)
- ``commit_assembly`` — make_commit + verify_commit through a
                        prewarmed SignatureCache (assembly/tally path
                        only; curve math stays off)
- ``gossip_pick``     — one steady-state gossip tick across all
                        peers' PeerVoteCursors (the incremental-
                        cursor fix: the old rescan was O(V) per peer
                        per tick → slope ~2 committee-wide)
- ``fanout_publish``  — FanoutHub._deliver to N subscribers sharing
                        one query group (O(N) enqueues of a shared
                        payload; per-subscriber encodes → slope >1
                        plus a constant blowup)

Exponents, not absolute walls: wall-clock budgets rot with the box,
but ``log(wall) ~ k*log(n)`` survives CPU scaling — the same
reasoning the reference's benchstat workflows apply to -benchtime
sweeps (types/validator_set_test.go BenchmarkUpdates).
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

try:
    import tomllib as _toml
except ImportError:  # pragma: no cover - py<3.11: same-API backport
    try:
        import tomli as _toml
    except ImportError:
        _toml = None

SIZES = (4, 16, 64, 128)

# a fixed hot path should be ~linear; 1.35 leaves headroom for
# allocator/cache noise at small n while still refusing anything
# genuinely super-linear (n^1.5 at 4->128 is a 5.6x blowup over n)
DEFAULT_EXPONENT_BUDGET = 1.35

DEFAULT_BUDGET_PATH = os.path.join("tools", "scaling_budgets.toml")

# injected sites carry the same name prefix inject_lock_inversion's
# probe locks do: chaos treats prefixed findings as EXPECTED
INJECTED_PREFIX = "chaos."


def default_budget_file(repo_root: Optional[str] = None) -> str:
    """Package-anchored like obs.budget.default_budget_file: the probe
    must resolve its budgets no matter the caller's cwd."""
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(root, DEFAULT_BUDGET_PATH)


def _parse_budget_toml_minimal(text: str) -> Dict[str, dict]:
    """Fallback reader for the exact shape scaling_budgets.toml uses
    ([scaling."site"] tables of scalar keys) so the probe still runs
    on a box with neither tomllib nor tomli."""
    out: Dict[str, dict] = {}
    cur: Optional[dict] = None
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            name = line[1:-1].strip()
            if name.startswith("scaling."):
                name = name[len("scaling."):].strip().strip('"')
                cur = out.setdefault(name, {})
            else:
                cur = None
            continue
        if cur is not None and "=" in line:
            k, v = (s.strip() for s in line.split("=", 1))
            try:
                cur[k] = float(v)
            except ValueError:
                cur[k] = v.strip('"')
    return out


def load_exponent_budgets(path: Optional[str] = None) -> Dict[str, float]:
    """{site: max_exponent} from tools/scaling_budgets.toml."""
    path = path or default_budget_file()
    if _toml is not None:
        with open(path, "rb") as f:
            raw = _toml.load(f)
        tables = raw.get("scaling") or {}
    else:  # pragma: no cover - no TOML reader tier
        with open(path, "r", encoding="utf-8") as f:
            tables = _parse_budget_toml_minimal(f.read())
    out: Dict[str, float] = {}
    for site, entry in tables.items():
        if not isinstance(entry, dict) or "max_exponent" not in entry:
            raise ValueError(
                f"scaling.{site!r}: expected a table with max_exponent"
            )
        out[site] = float(entry["max_exponent"])
    return out


def fit_exponent(
    sizes: Sequence[int], walls: Sequence[float]
) -> float:
    """Least-squares slope of log(wall) vs log(n): the empirical k in
    wall ~ C * n^k. O(1) sites fit k ~ 0, linear ~1, quadratic ~2."""
    if len(sizes) != len(walls) or len(sizes) < 2:
        raise ValueError("need >= 2 (size, wall) points")
    xs = [math.log(n) for n in sizes]
    ys = [math.log(max(w, 1e-12)) for w in walls]
    mx = sum(xs) / len(xs)
    my = sum(ys) / len(ys)
    denom = sum((x - mx) ** 2 for x in xs)
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom


@dataclass
class ScalingResult:
    """One site's fitted slope vs its budget (asdict-able for the
    bench checkpoint JSON and the chaos report)."""

    site: str
    sizes: tuple
    walls_s: tuple  # median wall per size, seconds
    exponent: float
    budget: float
    injected: bool = False

    @property
    def ok(self) -> bool:
        return self.exponent <= self.budget

    def as_dict(self) -> dict:
        return {
            "site": self.site,
            "sizes": list(self.sizes),
            "walls_us": [round(w * 1e6, 3) for w in self.walls_s],
            "exponent": round(self.exponent, 4),
            "budget": self.budget,
            "ok": self.ok,
            "injected": self.injected,
        }


def injected_result(r) -> bool:
    """True for results from an injected (``chaos.``-prefixed) site —
    chaos treats those breaches as EXPECTED, mirroring
    analysis/runtime.injected_finding."""
    site = r.site if isinstance(r, ScalingResult) else r.get("site", "")
    return str(site).startswith(INJECTED_PREFIX)


# --- site registry -------------------------------------------------------
#
# A site is ``setup(n) -> run`` where setup builds all n-sized state
# once per committee size and ``run()`` executes ONE unit of the hot
# path (one full-committee round of it). Timing reps are calibrated
# so each sample batch clears the wall floor.

SiteSetup = Callable[[int], Callable[[], object]]

_SITES: Dict[str, SiteSetup] = {}


def register_site(name: str, setup: SiteSetup) -> None:
    _SITES[name] = setup


def site_names() -> List[str]:
    return sorted(_SITES)


def synthetic_site(power: float, unit: int = 40) -> SiteSetup:
    """Pure-compute site whose work is exactly ``unit * n**power``
    loop iterations — the probe's own calibration fixture (tests
    bracket the fitted exponent) and the quadratic injection plant."""

    def setup(n: int) -> Callable[[], int]:
        iters = int(unit * (n ** power)) + 1

        def run() -> int:
            acc = 0
            for i in range(iters):
                acc += i
            return acc

        return run

    return setup


def inject_quadratic_site(
    sites: Optional[Dict[str, SiteSetup]] = None, unit: int = 6
) -> str:
    """Plant a deliberately O(n^2) site (chaos ``scaling_probe``
    fault with inject_quadratic): the probe must flag it or the run
    fails — detection proof, same contract as lock_inversion."""
    name = INJECTED_PREFIX + "injected_quadratic"
    (_SITES if sites is None else sites)[name] = synthetic_site(
        2.0, unit=unit
    )
    return name


# --- real sites ----------------------------------------------------------


def _committee(n: int):
    """(valset, votes, chain_id, height): n fake validators with
    deterministic 32-byte keys (sha-derived 20-byte addresses, no
    keygen — the probe measures the data plane, not Ed25519)."""
    from ..types.block import BlockID, PartSetHeader
    from ..types.validator_set import Validator, ValidatorSet
    from ..types.vote import PRECOMMIT, Vote
    from ..crypto.keys import PubKey

    chain_id = "scaling-probe"
    height = 3
    vals = [
        Validator(PubKey(bytes([7]) + i.to_bytes(31, "big")), 10)
        for i in range(n)
    ]
    vs = ValidatorSet(vals)
    block_id = BlockID(
        hash=b"\xab" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\xcd" * 32),
    )
    ts = 1_700_000_000_000_000_000
    votes = [
        Vote(
            type_=PRECOMMIT,
            height=height,
            round=0,
            block_id=block_id,
            timestamp_ns=ts,
            validator_address=v.address,
            validator_index=i,
            signature=bytes([i % 251 + 1]) * 64,
        )
        for i, v in enumerate(vs.validators)
    ]
    return vs, votes, chain_id, height


def _site_vote_add(n: int) -> Callable[[], object]:
    """Full committee through VoteSet.add_vote (signatures off): the
    path the total_voting_power memo fixed — unmemoized, each add
    resums O(V) powers and the committee round is O(V^2)."""
    from ..types.vote import PRECOMMIT
    from ..types.vote_set import VoteSet

    valset, votes, chain_id, height = _committee(n)

    def run():
        vs = VoteSet(
            chain_id, height, 0, PRECOMMIT, valset,
            verify_signatures=False,
        )
        for v in votes:
            vs.add_vote(v)
        return vs

    return run


def _site_commit_assembly(n: int) -> Callable[[], object]:
    """make_commit + verify_commit with every signature prewarmed in
    the SignatureCache: measures commit assembly, sign-bytes memo and
    tally — the O(V) floor — with the curve math cache-hit away."""
    from ..types import validation
    from ..types.signature_cache import SignatureCache
    from ..types.vote import PRECOMMIT
    from ..types.vote_set import VoteSet

    valset, votes, chain_id, height = _committee(n)
    vs = VoteSet(
        chain_id, height, 0, PRECOMMIT, valset, verify_signatures=False
    )
    for v in votes:
        vs.add_vote(v)
    cache = SignatureCache(size=max(4096, 4 * n))
    commit0 = vs.make_commit()
    key_by_addr = {
        val.address: val.pub_key.key_bytes for val in valset.validators
    }
    for cs in commit0.signatures:
        sb = validation._commit_sign_bytes(chain_id, commit0, cs)
        cache.add(sb, cs.signature, key_by_addr[cs.validator_address])

    def run():
        commit = vs.make_commit()
        validation.verify_commit(
            chain_id, valset, commit.block_id, height, commit, cache
        )
        return commit

    return run


def _site_gossip_pick(n: int) -> Callable[[], object]:
    """One steady-state gossip tick for a committee of n peers: every
    peer's PeerVoteCursor ingests + picks against fully-acked logs.
    The cursor fix makes each peer O(new + unacked) = O(1) here; the
    rescan it replaced paid O(V) per peer (slope ~2 committee-wide)."""
    from ..consensus.reactor import PeerRoundState, PeerVoteCursor, _vote_key
    from ..types.vote import PRECOMMIT, PREVOTE, Vote
    from ..types.vote_set import VoteSet

    valset, votes, chain_id, height = _committee(n)
    prevotes = VoteSet(
        chain_id, height, 0, PREVOTE, valset, verify_signatures=False
    )
    precommits = VoteSet(
        chain_id, height, 0, PRECOMMIT, valset, verify_signatures=False
    )
    for v in votes:
        precommits.add_vote(v)
        prevotes.add_vote(
            Vote(
                type_=PREVOTE,
                height=v.height,
                round=v.round,
                block_id=v.block_id,
                timestamp_ns=v.timestamp_ns,
                validator_address=v.validator_address,
                validator_index=v.validator_index,
                signature=v.signature,
            )
        )

    class _HVS:
        def prevotes(self, r):
            return prevotes if r == 0 else None

        def precommits(self, r):
            return precommits if r == 0 else None

    class _RS:
        pass

    rs = _RS()
    rs.height = height
    rs.round = 0
    rs.votes = _HVS()
    rs.last_commit = None

    prs = PeerRoundState(height=height, round=0)
    for src in (prevotes, precommits):
        for v in src.vote_log:
            prs.has_votes.add(_vote_key(v))

    cursors = [PeerVoteCursor() for _ in range(n)]
    for cur in cursors:
        cur.reset(height)
        cur.ingest(rs, prs)
        cur.due_votes(prs, 0.0, 1 << 30)  # drain: everything is acked

    def run():
        for cur in cursors:
            cur.ingest(rs, prs)
            cur.due_votes(prs, 0.0, 16)
        return cursors

    return run


def _site_fanout_publish(n: int) -> Callable[[], object]:
    """FanoutHub._deliver to n subscribers sharing one query group:
    one encode then n string splices + bounded enqueues per event
    (the ISSUE 15 fan-out contract — per-subscriber re-encodes would
    show up as a slope-preserving constant blowup here)."""
    from ..rpc.fanout import FanoutHub, FanoutSubscriber, _Group
    from ..types import events as ev

    class _MatchAll:
        def matches(self, attrs) -> bool:
            return True

    hub = FanoutHub(bus=None)
    group = _Group("probe='scaling'", _MatchAll())
    hub._groups[group.query_str] = group
    subs = []
    for i in range(n):
        sub = FanoutSubscriber(None, i, group.query_str, queue_size=64)
        group.members.add(sub)
        subs.append(sub)
    events = [
        ev.Event("scaling_probe", None, {"seq": str(i)}) for i in range(4)
    ]

    def run():
        for e in events:
            hub._deliver(e)
        for sub in subs:
            q = sub.queue
            while not q.empty():
                q.get_nowait()
        return hub.delivered

    return run


register_site("vote_add", _site_vote_add)
register_site("commit_assembly", _site_commit_assembly)
register_site("gossip_pick", _site_gossip_pick)
register_site("fanout_publish", _site_fanout_publish)


# --- probe driver --------------------------------------------------------


def time_site(
    setup: SiteSetup,
    sizes: Sequence[int] = SIZES,
    min_wall_s: float = 0.01,
    repeats: int = 3,
    max_reps: int = 20000,
) -> List[float]:
    """Median wall per committee size. Reps per sample batch are
    calibrated so each batch clears ``min_wall_s`` — small-n runs are
    microseconds and a single-shot wall would be timer noise."""
    walls: List[float] = []
    for n in sizes:
        run = setup(n)
        run()  # warm allocators / memos
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        reps = max(1, min(max_reps, math.ceil(min_wall_s / max(dt, 1e-9))))
        samples = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(reps):
                run()
            samples.append((time.perf_counter() - t0) / reps)
        samples.sort()
        walls.append(samples[len(samples) // 2])
    return walls


def run_probe(
    sites: Optional[Dict[str, SiteSetup]] = None,
    sizes: Sequence[int] = SIZES,
    budgets: Optional[Dict[str, float]] = None,
    min_wall_s: float = 0.01,
    repeats: int = 3,
) -> List[ScalingResult]:
    """Drive every site, fit exponents, judge against budgets.
    Injected (``chaos.``) sites fall back to the default budget —
    they exist to BREACH it."""
    if sites is None:
        sites = _SITES
    if budgets is None:
        try:
            budgets = load_exponent_budgets()
        except (OSError, ValueError):
            budgets = {}
    out: List[ScalingResult] = []
    for name in sorted(sites):
        walls = time_site(
            sites[name], sizes, min_wall_s=min_wall_s, repeats=repeats
        )
        out.append(
            ScalingResult(
                site=name,
                sizes=tuple(sizes),
                walls_s=tuple(walls),
                exponent=fit_exponent(sizes, walls),
                budget=budgets.get(name, DEFAULT_EXPONENT_BUDGET),
                injected=name.startswith(INJECTED_PREFIX),
            )
        )
    return out


def format_results(results: Sequence[ScalingResult]) -> str:
    """Aligned table, breaches first (chaos/bench log discipline)."""
    lines = [
        f"{'verdict':<8} {'site':<28} {'exponent':>9} {'budget':>7} "
        f"{'walls us @ ' + 'x'.join(str(s) for s in (results[0].sizes if results else SIZES))}"
    ]
    for r in sorted(results, key=lambda r: (r.ok, r.site)):
        walls = " ".join(f"{w * 1e6:.1f}" for w in r.walls_s)
        tag = "OK" if r.ok else ("PLANT" if r.injected else "OVER")
        lines.append(
            f"{tag:<8} {r.site:<28} {r.exponent:>9.3f} {r.budget:>7.2f} {walls}"
        )
    n_over = sum(1 for r in results if not r.ok and not r.injected)
    lines.append(
        "scaling verdict: "
        + ("PASS" if n_over == 0 else f"FAIL ({n_over} site(s) over budget)")
    )
    return "\n".join(lines)


# --- chaos drain ---------------------------------------------------------
#
# Mirrors the runtime sanitizer contract (analysis/runtime.py):
# the nemesis runs the probe mid-schedule, findings accumulate here,
# and chaos/net.py drains them into the report after the run —
# un-injected breaches become violations, a scheduled injection that
# the probe did NOT flag also becomes a violation.

_CHAOS_RESULTS: List[ScalingResult] = []


def probe_for_chaos(
    inject_quadratic: bool = False,
    sizes: Sequence[int] = (4, 16, 48),
    min_wall_s: float = 0.004,
) -> dict:
    """Nemesis entry point (chaos ``scaling_probe`` fault): smaller
    sizes + floor than the bench leg — the chaos run wants detection
    proof under load, not publication-grade medians."""
    sites = dict(_SITES)
    planted = None
    if inject_quadratic:
        planted = inject_quadratic_site(sites)
    results = run_probe(
        sites=sites, sizes=sizes, min_wall_s=min_wall_s, repeats=3
    )
    _CHAOS_RESULTS.extend(results)
    return {
        "sites": len(results),
        "injected": planted,
        "breaches": [r.site for r in results if not r.ok],
        "exponents": {r.site: round(r.exponent, 3) for r in results},
    }


def drain_chaos_results() -> List[ScalingResult]:
    out = list(_CHAOS_RESULTS)
    _CHAOS_RESULTS.clear()
    return out
