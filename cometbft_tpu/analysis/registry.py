"""Rule registry.

A rule is a callable ``check(ctx) -> Iterable[Finding]`` registered
under a stable id (``ASY101``) and a human name
(``blocking-call-in-async``).  Suppression comments and the baseline
refer to rules by either spelling.
"""
from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, NamedTuple

from .findings import Finding


class FileContext(NamedTuple):
    """Everything a rule gets to look at for one file."""

    path: str  # relative posix path used in findings
    tree: ast.Module
    source: str
    lines: List[str]


class Rule(NamedTuple):
    rule_id: str
    name: str
    doc: str
    check: Callable[[FileContext], Iterable[Finding]]


class ProjectRule(NamedTuple):
    """A whole-program rule: ``check(project) -> Iterable[Finding]``
    over the callgraph.Project model instead of one FileContext.
    Project rules may share a rule id with a file rule (the ASY102
    deep-chain upgrade reports under the same id as the single-file
    pass); suppressions and the baseline treat them identically."""

    rule_id: str
    name: str
    doc: str
    check: Callable[["object"], Iterable[Finding]]


_RULES: Dict[str, Rule] = {}
_PROJECT_RULES: Dict[str, ProjectRule] = {}


def rule(rule_id: str, name: str, doc: str):
    """Decorator registering ``check`` under ``rule_id``/``name``."""

    def deco(fn: Callable[[FileContext], Iterable[Finding]]):
        if rule_id in _RULES or any(
            r.name == name for r in _RULES.values()
        ):
            raise ValueError(f"duplicate rule {rule_id}/{name}")
        _RULES[rule_id] = Rule(rule_id, name, fn.__doc__ or doc, fn)
        return fn

    return deco


def project_rule(rule_id: str, name: str, doc: str):
    """Decorator registering an interprocedural rule."""

    def deco(fn):
        if rule_id in _PROJECT_RULES:
            raise ValueError(f"duplicate project rule {rule_id}")
        _PROJECT_RULES[rule_id] = ProjectRule(
            rule_id, name, fn.__doc__ or doc, fn
        )
        return fn

    return deco


def all_rules() -> List[Rule]:
    _load_builtin()
    return [r for _, r in sorted(_RULES.items())]


def all_project_rules() -> List[ProjectRule]:
    _load_builtin()
    return [r for _, r in sorted(_PROJECT_RULES.items())]


def resolve(spec: str) -> str | None:
    """Map an id or name (as written in a suppression) to a rule id."""
    _load_builtin()
    spec = spec.strip()
    if spec in _RULES or spec in _PROJECT_RULES:
        return spec
    for r in _RULES.values():
        if r.name == spec:
            return r.rule_id
    for pr in _PROJECT_RULES.values():
        if pr.name == spec:
            return pr.rule_id
    return None


def _load_builtin() -> None:
    # Import for side effect of registration; idempotent.
    from . import rules  # noqa: F401
