"""Rule registry.

A rule is a callable ``check(ctx) -> Iterable[Finding]`` registered
under a stable id (``ASY101``) and a human name
(``blocking-call-in-async``).  Suppression comments and the baseline
refer to rules by either spelling.
"""
from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, NamedTuple

from .findings import Finding


class FileContext(NamedTuple):
    """Everything a rule gets to look at for one file."""

    path: str  # relative posix path used in findings
    tree: ast.Module
    source: str
    lines: List[str]


class Rule(NamedTuple):
    rule_id: str
    name: str
    doc: str
    check: Callable[[FileContext], Iterable[Finding]]


_RULES: Dict[str, Rule] = {}


def rule(rule_id: str, name: str, doc: str):
    """Decorator registering ``check`` under ``rule_id``/``name``."""

    def deco(fn: Callable[[FileContext], Iterable[Finding]]):
        if rule_id in _RULES or any(
            r.name == name for r in _RULES.values()
        ):
            raise ValueError(f"duplicate rule {rule_id}/{name}")
        _RULES[rule_id] = Rule(rule_id, name, fn.__doc__ or doc, fn)
        return fn

    return deco


def all_rules() -> List[Rule]:
    _load_builtin()
    return [r for _, r in sorted(_RULES.items())]


def resolve(spec: str) -> str | None:
    """Map an id or name (as written in a suppression) to a rule id."""
    _load_builtin()
    spec = spec.strip()
    if spec in _RULES:
        return spec
    for r in _RULES.values():
        if r.name == spec:
            return r.rule_id
    return None


def _load_builtin() -> None:
    # Import for side effect of registration; idempotent.
    from . import rules  # noqa: F401
