"""Whole-program symbol table + project call graph.

The per-file rules (rules/async_rules.py) see one module at a time,
so a chain like ``self.pool.stop()`` — an object whose methods live
in another file — is invisible to them (docs/LINT.md documented the
blind spot explicitly). This module builds the interprocedural model
the deeper ASY rules need:

- a **symbol table** over every scanned module: classes with their
  methods (decorators do not hide a def), module-level functions,
  nested defs, and per-module import aliases;
- **attribute-type inference** from ``__init__`` assignments:
  ``self.pool = BlockPool(...)`` types ``self.pool`` as
  ``BlockPool``; an annotated parameter stored on self
  (``def __init__(self, wal: WAL): self.wal = wal``) and
  ``self.x: Foo`` annotations type the same way;
- a **call graph**: one edge per resolved call expression, with the
  source location, the written spelling, and whether the call was
  awaited. Resolution handles ``self``/``cls`` chains through the
  inferred attribute types, inheritance + ``super()`` dispatch,
  imported names, class constructors (edge to ``__init__``),
  ``functools.partial(f, ...)`` (edge to ``f``), and lambda bodies
  (a lambda's callees are attributed to the enclosing function).

Everything is name-based and best-effort, like the rest of bftlint:
an unresolvable call simply creates no edge, so the interprocedural
rules under-approximate rather than guess. Pure stdlib — importing
this module must never pull in jax.

Reachability helpers answer the question the rules ask: *can this
function, executed synchronously, hit a blocking call* — traversing
only sync callees (calling an ``async def`` without awaiting it
executes nothing) and stopping at offload seams (a function
reference passed to ``asyncio.to_thread`` / ``run_in_executor`` /
``Thread(target=...)`` is an argument, not a call, so no edge exists
in the first place).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .astutil import dotted

# Sync calls that block the calling thread: the ASY101 name set plus
# the barrier-ish leaves that only matter through a call chain (a
# direct os.fsync on a hot plane is ASY111's business; REACHED from
# an async def it is a loop stall regardless of module).
BLOCKING_LEAVES: Dict[str, str] = {
    "time.sleep": "blocks the thread",
    "os.system": "blocks on a subprocess",
    "os.wait": "blocks on a subprocess",
    "os.waitpid": "blocks on a subprocess",
    "os.fsync": "is a disk barrier",
    "os.fdatasync": "is a disk barrier",
    "subprocess.run": "blocks on a subprocess",
    "subprocess.call": "blocks on a subprocess",
    "subprocess.check_call": "blocks on a subprocess",
    "subprocess.check_output": "blocks on a subprocess",
    "urllib.request.urlopen": "does sync network I/O",
    "requests.get": "does sync network I/O",
    "requests.post": "does sync network I/O",
    "requests.put": "does sync network I/O",
    "requests.delete": "does sync network I/O",
    "requests.request": "does sync network I/O",
    "socket.create_connection": "does sync network I/O",
    "socket.getaddrinfo": "does sync DNS resolution",
    "sqlite3.connect": "does sync disk I/O",
    "select.select": "blocks on file descriptors",
}

# method-suffix leaves: a blocking call regardless of receiver
# spelling (``<ticket>.wait()``, ``<thread>.join()``, ``<proc>
# .communicate()``); ``.wait`` / ``.join`` need a lock/thread-ish or
# event-ish receiver to avoid flagging asyncio.Event().wait-style
# awaitables — we require the call NOT be awaited at the site, which
# the builder records, and leave the judgment to the rule.
BLOCKING_METHOD_SUFFIXES: Dict[str, str] = {
    "getaddrinfo": "does sync DNS resolution",
}


@dataclass
class CallSite:
    """One resolved call expression inside a function body."""

    callee: str  # qualname of the resolved FunctionInfo
    spelling: str  # the dotted source spelling, e.g. "self.pool.stop"
    line: int
    col: int
    awaited: bool


@dataclass
class BlockingSite:
    """One known-blocking leaf call inside a function body."""

    spelling: str
    reason: str
    line: int
    col: int


@dataclass
class FunctionInfo:
    qualname: str  # "path::Class.name" / "path::name" / nested "a.<locals>.b"
    name: str
    path: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    class_name: Optional[str] = None
    calls: List[CallSite] = field(default_factory=list)
    blocking: List[BlockingSite] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)  # dotted spellings
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)


def walk_with_lambdas(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested def/class
    bodies, but INCLUDING lambda bodies: a lambda's callees belong to
    the enclosing function for reachability purposes (it is built and
    almost always invoked from the same execution context)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(child))


def _is_super_call(func: ast.AST) -> Optional[str]:
    """``super().m`` -> "m", else None."""
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Call)
        and dotted(func.value.func) == "super"
    ):
        return func.attr
    return None


class Project:
    """The whole-program model: build once, query from project rules.

    ``sanctioned(path, line) -> bool`` marks blocking-leaf call sites
    that are deliberate, calibrated sinks (the engine wires it to
    ``# bftlint: disable=ASY114`` suppressions in the LEAF's own
    file): a sanctioned leaf is not a blocking leaf at all, so every
    chain through it vanishes for ASY114 *and* ASY115 — the one
    escape hatch for seams like the WAL barrier, which must carry a
    justification comment at the leaf."""

    def __init__(
        self,
        files: List[Tuple[str, ast.Module]],
        sanctioned=None,
        suppressed=None,
    ):
        self.files = files
        self._sanctioned = sanctioned or (lambda path, line: False)
        # ``suppressed(path, line, rule_id)`` — generic per-line
        # suppression lookup for rules that sanction LEAF lines in a
        # different file than the finding (ASY116's listener chains);
        # the engine wires it to the parsed suppression tables
        self._suppressed = suppressed or (
            lambda path, line, rule_id: False
        )
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, List[ClassInfo]] = {}  # by bare name
        self.module_functions: Dict[str, Dict[str, FunctionInfo]] = {}
        self.module_classes: Dict[str, Dict[str, ClassInfo]] = {}
        # per-module import table: local name -> dotted source ("pkg.mod"
        # for ``import pkg.mod``/aliases, "pkg.mod.obj" for from-imports)
        self.imports: Dict[str, Dict[str, str]] = {}
        self._blocking_chain_cache: Dict[str, Optional[List[str]]] = {}
        for path, tree in files:
            self._index_module(path, tree)
        for cls_list in self.classes.values():
            for ci in cls_list:
                self._infer_attr_types(ci)
        for fi in list(self.functions.values()):
            self._extract_calls(fi)

    # --- indexing -----------------------------------------------------

    def _index_module(self, path: str, tree: ast.Module) -> None:
        self.module_functions[path] = {}
        self.module_classes[path] = {}
        imports: Dict[str, str] = {}
        self.imports[path] = imports
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    imports[alias.asname or alias.name] = (
                        f"{mod}.{alias.name}" if mod else alias.name
                    )
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(path, node, class_name=None)
            elif isinstance(node, ast.ClassDef):
                self._index_class(path, node)

    def _index_class(self, path: str, node: ast.ClassDef) -> None:
        ci = ClassInfo(
            name=node.name,
            path=path,
            node=node,
            bases=[b for base in node.bases if (b := dotted(base))],
        )
        self.classes.setdefault(node.name, []).append(ci)
        self.module_classes[path][node.name] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._add_function(path, item, class_name=node.name)
                ci.methods[item.name] = fi

    def _add_function(
        self, path: str, node, class_name: Optional[str], prefix: str = ""
    ) -> FunctionInfo:
        base = f"{class_name}." if class_name else ""
        qual = f"{path}::{prefix}{base}{node.name}"
        fi = FunctionInfo(
            qualname=qual,
            name=node.name,
            path=path,
            node=node,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            class_name=class_name,
        )
        self.functions[qual] = fi
        if not class_name and not prefix:
            self.module_functions[path][node.name] = fi
        # nested defs: registered so a local call to the name resolves
        for child in ast.walk(node):
            if child is node:
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested_prefix = f"{prefix}{base}{node.name}.<locals>."
                nq = f"{path}::{nested_prefix}{child.name}"
                if nq not in self.functions:
                    self._add_function(
                        path, child, class_name=None,
                        prefix=nested_prefix,
                    )
        return fi

    # --- attribute-type inference -------------------------------------

    def _class_of_value(
        self, path: str, value: ast.AST, ann_params: Dict[str, str]
    ) -> Optional[str]:
        """Class NAME for an assignment RHS: a constructor call, a
        bare copy of an annotated parameter, or None."""
        if isinstance(value, ast.Call):
            name = dotted(value.func)
            if name is None:
                return None
            last = name.rsplit(".", 1)[-1]
            if self._resolve_class(path, last) is not None:
                return last
            return None
        if isinstance(value, ast.Name):
            return ann_params.get(value.id)
        return None

    @staticmethod
    def _ann_name(ann: Optional[ast.AST]) -> Optional[str]:
        if ann is None:
            return None
        name = dotted(ann)
        if name:
            return name.rsplit(".", 1)[-1]
        # Optional[Foo] / "Foo" string annotations
        if isinstance(ann, ast.Subscript):
            inner = ann.slice
            return Project._ann_name(inner)
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return ann.value.rsplit(".", 1)[-1].strip("'\" ")
        return None

    def _infer_attr_types(self, ci: ClassInfo) -> None:
        """``self.x`` types from assignments; ``__init__`` first so
        the constructor's view wins over later re-assignments."""
        ordered = sorted(
            ci.methods.values(), key=lambda m: m.name != "__init__"
        )
        for m in ordered:
            ann_params: Dict[str, str] = {}
            args = m.node.args
            for p in args.posonlyargs + args.args + args.kwonlyargs:
                t = self._ann_name(p.annotation)
                if t and self._resolve_class(ci.path, t) is not None:
                    ann_params[p.arg] = t
            for node in walk_with_lambdas(m.node):
                target = None
                value = None
                ann = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value, ann = node.target, node.value, node.annotation
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    continue
                attr = target.attr
                if attr in ci.attr_types:
                    continue  # first writer (init-first order) wins
                t = self._ann_name(ann) if ann is not None else None
                if t is None and value is not None:
                    t = self._class_of_value(ci.path, value, ann_params)
                if t and self._resolve_class(ci.path, t) is not None:
                    ci.attr_types[attr] = t

    # --- resolution ---------------------------------------------------

    def _resolve_class(
        self, path: str, name: str
    ) -> Optional[ClassInfo]:
        """Class by bare name: same module first, then the import
        table, then a unique global match (ambiguity -> None: the
        rules must under-approximate, never guess)."""
        own = self.module_classes.get(path, {}).get(name)
        if own is not None:
            return own
        src = self.imports.get(path, {}).get(name)
        candidates = self.classes.get(name, [])
        if src is not None and candidates:
            want = src.replace(".", "/")
            for ci in candidates:
                mod = ci.path[:-3] if ci.path.endswith(".py") else ci.path
                if mod.endswith(want.rsplit("/", 1)[0]) or want.endswith(
                    mod.rsplit("/", 1)[-1]
                ):
                    return ci
            return candidates[0]
        if len(candidates) == 1:
            return candidates[0]
        return None

    def resolve_method(
        self, ci: Optional[ClassInfo], name: str,
        _seen: Optional[Set[str]] = None,
    ) -> Optional[FunctionInfo]:
        """Method lookup through the inheritance chain (C3-ish: own
        methods, then bases left-to-right, cycle-safe)."""
        if ci is None:
            return None
        seen = _seen or set()
        key = f"{ci.path}::{ci.name}"
        if key in seen:
            return None
        seen.add(key)
        if name in ci.methods:
            return ci.methods[name]
        for base in ci.bases:
            bci = self._resolve_class(ci.path, base.rsplit(".", 1)[-1])
            hit = self.resolve_method(bci, name, seen)
            if hit is not None:
                return hit
        return None

    def _class_of(self, fi: FunctionInfo) -> Optional[ClassInfo]:
        if fi.class_name is None:
            return None
        return self.module_classes.get(fi.path, {}).get(fi.class_name)

    def _local_var_types(self, fi: FunctionInfo) -> Dict[str, str]:
        """``x = Foo(...)`` / annotated params inside one function."""
        out: Dict[str, str] = {}
        args = fi.node.args
        for p in args.posonlyargs + args.args + args.kwonlyargs:
            t = self._ann_name(p.annotation)
            if t and self._resolve_class(fi.path, t) is not None:
                out[p.arg] = t
        for node in walk_with_lambdas(fi.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                name = dotted(node.value.func)
                if name is None:
                    continue
                last = name.rsplit(".", 1)[-1]
                if self._resolve_class(fi.path, last) is not None:
                    out.setdefault(node.targets[0].id, last)
        return out

    def resolve_call(
        self,
        fi: FunctionInfo,
        call: ast.Call,
        local_types: Optional[Dict[str, str]] = None,
    ) -> Optional[FunctionInfo]:
        func = call.func
        # functools.partial(f, ...): the edge goes to f
        fname = dotted(func)
        if fname in ("functools.partial", "partial") and call.args:
            inner = call.args[0]
            iname = dotted(inner)
            if iname is not None:
                return self._resolve_dotted(fi, iname, local_types)
            return None
        sup = _is_super_call(func)
        if sup is not None:
            ci = self._class_of(fi)
            if ci is None:
                return None
            for base in ci.bases:
                bci = self._resolve_class(
                    ci.path, base.rsplit(".", 1)[-1]
                )
                hit = self.resolve_method(bci, sup)
                if hit is not None:
                    return hit
            return None
        if fname is None:
            return None
        return self._resolve_dotted(fi, fname, local_types)

    def _resolve_dotted(
        self,
        fi: FunctionInfo,
        name: str,
        local_types: Optional[Dict[str, str]] = None,
    ) -> Optional[FunctionInfo]:
        parts = name.split(".")
        ci = self._class_of(fi)
        if parts[0] in ("self", "cls") and ci is not None:
            cur: Optional[ClassInfo] = ci
            for seg in parts[1:-1]:
                tname = cur.attr_types.get(seg) if cur else None
                cur = (
                    self._resolve_class(cur.path, tname)
                    if (cur and tname)
                    else None
                )
                if cur is None:
                    return None
            return self.resolve_method(cur, parts[-1])
        if len(parts) == 1:
            # nested def in this function
            prefix = fi.qualname.split("::", 1)[1]
            nested = self.functions.get(
                f"{fi.path}::{prefix}.<locals>.{parts[0]}"
            )
            if nested is not None:
                return nested
            own = self.module_functions.get(fi.path, {}).get(parts[0])
            if own is not None:
                return own
            # class constructor -> __init__
            cls = self._resolve_class(fi.path, parts[0])
            if cls is not None:
                return self.resolve_method(cls, "__init__")
            # imported function
            src = self.imports.get(fi.path, {}).get(parts[0])
            if src is not None:
                return self._function_from_import(src, parts[0])
            return None
        # a.b(...): a is a local var / param with an inferred type,
        # an imported module, or a class (static-ish dispatch)
        head, tail = parts[0], parts[1:]
        if local_types is None:
            local_types = self._local_var_types(fi)
        tname = local_types.get(head)
        if tname is not None:
            cur = self._resolve_class(fi.path, tname)
            for seg in tail[:-1]:
                t2 = cur.attr_types.get(seg) if cur else None
                cur = (
                    self._resolve_class(cur.path, t2)
                    if (cur and t2)
                    else None
                )
            return self.resolve_method(cur, tail[-1])
        cls = self._resolve_class(fi.path, head)
        if cls is not None and len(tail) == 1:
            return self.resolve_method(cls, tail[0])
        src = self.imports.get(fi.path, {}).get(head)
        if src is not None and len(tail) == 1:
            return self._function_from_import(
                f"{src}.{tail[0]}", tail[0]
            )
        return None

    def _function_from_import(
        self, src: str, name: str
    ) -> Optional[FunctionInfo]:
        """Match an import source like ``..utils.tasks.spawn`` (or
        ``cometbft_tpu.utils.tasks`` + name) to an indexed function by
        module-path suffix."""
        mod_path = src.rsplit(".", 1)[0] if src.endswith(
            f".{name}"
        ) else src
        want = mod_path.replace(".", "/")
        best: Optional[FunctionInfo] = None
        n = 0
        for path, fns in self.module_functions.items():
            fn = fns.get(name)
            if fn is None:
                continue
            mod = path[:-3] if path.endswith(".py") else path
            if want and (mod.endswith(want) or want.endswith(
                mod.rsplit("/", 1)[-1]
            )):
                return fn
            best = fn
            n += 1
        return best if n == 1 else None

    # --- call extraction ----------------------------------------------

    def _extract_calls(self, fi: FunctionInfo) -> None:
        awaited_ids: Set[int] = set()
        for node in walk_with_lambdas(fi.node):
            if isinstance(node, ast.Await) and isinstance(
                node.value, ast.Call
            ):
                awaited_ids.add(id(node.value))
        local_types = self._local_var_types(fi)
        for node in walk_with_lambdas(fi.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is not None:
                if name in BLOCKING_LEAVES:
                    if not self._sanctioned(fi.path, node.lineno):
                        fi.blocking.append(
                            BlockingSite(
                                name, BLOCKING_LEAVES[name],
                                node.lineno, node.col_offset,
                            )
                        )
                    continue
                last = name.rsplit(".", 1)[-1]
                if (
                    last in BLOCKING_METHOD_SUFFIXES
                    and id(node) not in awaited_ids
                ):
                    if not self._sanctioned(fi.path, node.lineno):
                        fi.blocking.append(
                            BlockingSite(
                                name, BLOCKING_METHOD_SUFFIXES[last],
                                node.lineno, node.col_offset,
                            )
                        )
                    continue
            callee = self.resolve_call(fi, node, local_types)
            if callee is None or callee.qualname == fi.qualname:
                continue
            spelling = name or f"super().{_is_super_call(node.func)}"
            if name in ("functools.partial", "partial") and node.args:
                # the edge goes to the wrapped function; name IT
                spelling = dotted(node.args[0]) or callee.name
            fi.calls.append(
                CallSite(
                    callee=callee.qualname,
                    spelling=spelling,
                    line=node.lineno,
                    col=node.col_offset,
                    awaited=id(node) in awaited_ids,
                )
            )

    # --- reachability -------------------------------------------------

    def blocking_chain(self, qualname: str) -> Optional[List[str]]:
        """Spelling chain from this function to a known-blocking leaf
        through SYNC execution: own leaves first, then sync callees
        (an async callee does not run when merely called; awaiting it
        is the awaited function's own problem, reported there).
        Returns e.g. ``["self._flush", "os.fsync"]`` or None.
        Memoized; cycle-safe (a cycle contributes nothing)."""
        cache = self._blocking_chain_cache
        if qualname in cache:
            return cache[qualname]
        cache[qualname] = None  # in-progress sentinel: cycles stop here
        fi = self.functions.get(qualname)
        if fi is None:
            return None
        best: Optional[List[str]] = None
        if fi.blocking:
            site = fi.blocking[0]
            best = [site.spelling]
        else:
            for cs in fi.calls:
                callee = self.functions.get(cs.callee)
                if callee is None or callee.is_async:
                    continue
                sub = self.blocking_chain(cs.callee)
                if sub is not None and (
                    best is None or len(sub) + 1 < len(best)
                ):
                    best = [cs.spelling] + sub
        cache[qualname] = best
        return best

    def blocking_site(self, qualname: str) -> Optional[BlockingSite]:
        """The leaf at the end of blocking_chain(qualname)."""
        fi = self.functions.get(qualname)
        if fi is None:
            return None
        if fi.blocking:
            return fi.blocking[0]
        chain = self.blocking_chain(qualname)
        if not chain:
            return None
        for cs in fi.calls:
            sub = self.blocking_chain(cs.callee)
            if sub is not None and [cs.spelling] + sub == chain:
                return self.blocking_site(cs.callee)
        return None
