"""bftlint: stdlib-ast static analysis for cometbft_tpu.

Two rule families guard the two failure classes that silently kill
BFT throughput: async-safety (a blocked or starved event loop stalls
every reactor at once) and JAX hot-path hygiene (a host sync or
recompile inside the Ed25519 verify path collapses batch throughput).
See docs/LINT.md for the rule catalogue.

Public API:
    analyze_source(src, path) -> [Finding]   (unit-test entry point)
    run(paths)               -> [Finding]    (filesystem walk)
    main(argv)               -> exit code    (CLI)
"""
from .cli import main
from .engine import analyze_source, run
from .findings import Finding
from .registry import all_rules

__all__ = ["Finding", "all_rules", "analyze_source", "main", "run"]
