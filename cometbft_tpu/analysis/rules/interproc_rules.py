"""Interprocedural async-safety rules (whole-program pass).

These run over the callgraph.Project model (symbol table + inferred
attribute types + project call graph), so they see through the exact
blind spot docs/LINT.md documented for the single-file pass: "a
deeper chain like ``self.pool.stop()`` targets an object whose
methods the single-file pass cannot see".

- **ASY114 transitive-blocking-call** — a sync helper that blocks
  (time.sleep, sync socket/sqlite/subprocess, fsync) reachable from
  an ``async def`` in a hot plane through ANY call chain. The direct
  form is ASY101; this is the same loop stall hidden one or more
  frames down.
- **ASY115 await-holding-lock** — blocking work reached while a lock
  is held (``with <threading lock>`` or ``async with <asyncio
  lock>``), directly or through sync callees: the exact shape of the
  PR 11 fsync-held-inside-the-append-lock 10x liveness loss. The
  direct await-under-sync-lock form is ASY105; this rule adds the
  interprocedural (and the async-lock) half.
- **ASY102 (deep-chain upgrade)** — ``self.pool.stop()`` as a bare
  statement where attribute-type inference proves ``stop`` is an
  ``async def``: the coroutine is created and dropped, it never
  runs. Reported under the same id as the single-file ASY102.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from ..astutil import dotted
from ..callgraph import BLOCKING_LEAVES, Project, walk_with_lambdas
from ..findings import Finding
from ..registry import project_rule
from .async_rules import _HOT_PLANE_PREFIXES, _lockish

# where a transitively-blocking call from async context is a
# hot-plane loop stall (ASY109's package list + node/: the node's
# start/shutdown paths run on the same loop as every reactor)
_ASY114_PREFIXES = _HOT_PLANE_PREFIXES + ("cometbft_tpu/node/",)


def _in_scope(path: str, prefixes) -> bool:
    p = path.replace("\\", "/")
    return any(pref in p for pref in prefixes)


def _region_nodes(with_node) -> Iterator[ast.AST]:
    """Every node executed while the with-block's locks are held
    (lambda bodies included, nested defs excluded)."""
    for stmt in with_node.body:
        yield stmt
        yield from walk_with_lambdas(stmt)


def _chain_msg(project: Project, first_spelling: str,
               callee_qual: str) -> Optional[str]:
    chain = project.blocking_chain(callee_qual)
    if chain is None:
        return None
    site = project.blocking_site(callee_qual)
    reason = f" ({site.reason})" if site is not None else ""
    return " -> ".join([f"`{first_spelling}`"] + chain) + reason


@project_rule(
    "ASY114",
    "transitive-blocking-call",
    "a sync helper that blocks (sleep / sync I/O / subprocess / "
    "fsync) is reachable from an async def in a hot plane through a "
    "call chain; the loop stalls exactly as if the blocking call "
    "were inline (ASY101), it is just hidden N frames down",
)
def transitive_blocking_call(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for fi in project.functions.values():
        if not fi.is_async or not _in_scope(fi.path, _ASY114_PREFIXES):
            continue
        for cs in fi.calls:
            callee = project.functions.get(cs.callee)
            if callee is None or callee.is_async:
                continue  # async callee blocks are ITS findings
            msg = _chain_msg(project, cs.spelling, cs.callee)
            if msg is None:
                continue
            out.append(
                Finding(
                    fi.path, cs.line, cs.col,
                    "ASY114", "transitive-blocking-call",
                    f"call chain from `async def {fi.name}` reaches "
                    f"a blocking call: {msg} — the event loop parks "
                    "for the whole chain; offload the blocking leaf "
                    "(asyncio.to_thread / executor) or make the "
                    "helper loop-safe",
                )
            )
    return out


def _lock_regions(fi) -> Iterator[tuple]:
    """(with_node, lock_spelling, is_async_lock) for every lock-ish
    with-block in this function."""
    for node in walk_with_lambdas(fi.node):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        held = [
            n
            for item in node.items
            if (n := _lockish(item.context_expr)) is not None
        ]
        if held:
            yield node, held[0], isinstance(node, ast.AsyncWith)


@project_rule(
    "ASY115",
    "await-holding-lock",
    "blocking work (sleep / sync I/O / fsync) runs while a lock is "
    "held — directly or through sync callees. Every other "
    "acquirer (and with an asyncio lock, every waiter's task) "
    "queues behind the stall: the PR 11 fsync-inside-the-append-"
    "lock shape, worth 10x liveness",
)
def await_holding_lock(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for fi in project.functions.values():
        if not _in_scope(fi.path, _ASY114_PREFIXES):
            continue
        local_types = None
        for with_node, lock_name, is_async_lock in _lock_regions(fi):
            kind = "async lock" if is_async_lock else "lock"
            for node in _region_nodes(with_node):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name in BLOCKING_LEAVES:
                    if project._sanctioned(fi.path, node.lineno):
                        continue  # sanctioned sink: same contract as
                        # chains through it (docs/LINT.md)
                    out.append(
                        Finding(
                            fi.path, node.lineno, node.col_offset,
                            "ASY115", "await-holding-lock",
                            f"`{name}` ({BLOCKING_LEAVES[name]}) "
                            f"while `{lock_name}` ({kind}) is held "
                            f"in `{fi.name}`: every contender queues "
                            "behind the stall — move the blocking "
                            "work outside the critical section "
                            "(the WAL seam fsyncs on a dup'd fd "
                            "OUTSIDE its append lock for exactly "
                            "this reason)",
                        )
                    )
                    continue
                if local_types is None:
                    local_types = project._local_var_types(fi)
                callee = project.resolve_call(fi, node, local_types)
                if callee is None or callee.is_async:
                    continue
                msg = _chain_msg(
                    project, name or callee.name, callee.qualname
                )
                if msg is None:
                    continue
                out.append(
                    Finding(
                        fi.path, node.lineno, node.col_offset,
                        "ASY115", "await-holding-lock",
                        f"call chain {msg} runs while `{lock_name}` "
                        f"({kind}) is held in `{fi.name}`: every "
                        "contender queues behind the blocking leaf "
                        "— move it outside the critical section or "
                        "hand it to the WAL/offload seam",
                    )
                )
    return out


# DB-write leaves by method spelling: a `*.write_batch(...)` /
# `*.executemany(...)` call is a sync disk write regardless of the
# receiver's inferred type (the KV seam is an abstract base, so
# type-resolved chains die at the interface — the spelling doesn't)
_DB_WRITE_SUFFIXES = {
    "write_batch": "is a sync DB batch write",
    "executemany": "is a sync DB write",
    "fsync": "is a disk barrier",
    "fdatasync": "is a disk barrier",
}

# listener BFS bound: chains deeper than this are beyond what a
# reviewer can audit anyway and the walk must terminate on cycles
_ASY116_MAX_DEPTH = 8


def _listener_blocking_chain(
    project: Project, start, suppressed
) -> Optional[str]:
    """BFS from a sync-listener callback through resolved SYNC
    callees; returns a rendered chain when any reachable function
    contains a blocking leaf (BLOCKING_LEAVES or a DB-write
    spelling), else None. Leaf lines suppressed for ASY116 in their
    own file are sanctioned (same escape-hatch contract as ASY114's
    sinks — justification comment required)."""
    seen = {start.qualname}
    queue = [(start, [f"`{start.name}`"], 0)]
    while queue:
        fn, chain, depth = queue.pop(0)
        for node in walk_with_lambdas(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            reason = BLOCKING_LEAVES.get(name) or _DB_WRITE_SUFFIXES.get(
                name.rsplit(".", 1)[-1]
            )
            if reason is None:
                continue
            if suppressed(fn.path, node.lineno):
                continue
            return " -> ".join(chain + [f"`{name}` ({reason})"])
        if depth >= _ASY116_MAX_DEPTH:
            continue
        for cs in fn.calls:
            callee = project.functions.get(cs.callee)
            if (
                callee is None
                or callee.is_async
                or callee.qualname in seen
            ):
                continue
            seen.add(callee.qualname)
            queue.append(
                (callee, chain + [f"`{cs.spelling}`"], depth + 1)
            )
    return None


@project_rule(
    "ASY116",
    "sync-listener-blocking-call",
    "a bus.add_sync_listener callback reaches a blocking leaf (DB "
    "write, fsync, sync I/O) through its call chain: sync listeners "
    "run INSIDE every publish, on the publisher's thread — the "
    "consensus finalize path pays the write. Accumulate in memory "
    "and flush from a bounded async drain instead (the "
    "state/indexer.py shape)",
)
def sync_listener_blocking_call(project: Project) -> List[Finding]:
    def suppressed(path: str, line: int) -> bool:
        return project._suppressed(path, line, "ASY116")

    out: List[Finding] = []
    for fi in project.functions.values():
        for node in walk_with_lambdas(fi.node):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = dotted(node.func)
            if name is None or not name.endswith("add_sync_listener"):
                continue
            cb_name = dotted(node.args[0])
            if cb_name is None:
                continue
            cb = project._resolve_dotted(fi, cb_name)
            if cb is None or cb.is_async:
                continue
            msg = _listener_blocking_chain(project, cb, suppressed)
            if msg is None:
                continue
            out.append(
                Finding(
                    fi.path, node.lineno, node.col_offset,
                    "ASY116", "sync-listener-blocking-call",
                    f"sync listener `{cb_name}` registered here "
                    f"reaches a blocking call: {msg} — every "
                    "bus.publish (the consensus finalize path "
                    "included) pays it inline; accumulate in memory "
                    "and flush from a bounded async drain "
                    "(state/indexer.py IndexerService)",
                )
            )
    return out


@project_rule(
    "ASY102",
    "unawaited-coroutine-deep",
    "deep-chain upgrade of ASY102: `self.pool.stop()` as a bare "
    "statement where the inferred attribute types prove `stop` is "
    "an async def — the coroutine is created and dropped, it never "
    "runs (the documented single-file blind spot)",
)
def unawaited_coroutine_deep(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for fi in project.functions.values():
        for node in walk_with_lambdas(fi.node):
            if not (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
            ):
                continue
            call = node.value
            name = dotted(call.func)
            if name is None:
                continue
            parts = name.split(".")
            # exactly the deep chains the single-file rule documents
            # as invisible: `self.a.b()` and deeper (len==2 is the
            # file rule's exact `self.x()` case)
            if parts[0] not in ("self", "cls") or len(parts) < 3:
                continue
            callee = project._resolve_dotted(fi, name)
            if callee is None or not callee.is_async:
                continue
            out.append(
                Finding(
                    fi.path, node.lineno, node.col_offset,
                    "ASY102", "unawaited-coroutine",
                    f"`{name}(...)` resolves (via inferred attribute "
                    f"types) to `async def {callee.name}` — the "
                    "coroutine is created and dropped, it never "
                    "runs; await it or wrap it in a retained task "
                    "(utils.tasks.spawn)",
                )
            )
    return out
