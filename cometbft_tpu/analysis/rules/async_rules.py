"""Async-safety rules (ASY1xx).

These target the reactor/p2p/rpc layers: a single blocked event loop
stalls every peer connection at once, and a swallowed CancelledError
turns clean shutdown into a hang.  They are the Python analogue of
the `go vet` + race-detector discipline upstream CometBFT relies on.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from ..astutil import body_awaits, dotted, walk_in_function
from ..findings import Finding
from ..registry import FileContext, rule

# Call targets that block the calling thread.  Name-based: we cannot
# type-infer, but these dotted spellings are unambiguous in practice.
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep blocks the event loop; use "
    "`await asyncio.sleep` or `asyncio.to_thread`",
    "os.system": "os.system blocks; use asyncio.create_subprocess_*",
    "os.wait": "os.wait blocks the loop",
    "os.waitpid": "os.waitpid blocks the loop",
    "subprocess.run": "subprocess.run blocks; use "
    "asyncio.create_subprocess_exec",
    "subprocess.call": "subprocess.call blocks the loop",
    "subprocess.check_call": "subprocess.check_call blocks the loop",
    "subprocess.check_output": "subprocess.check_output blocks the loop",
    "urllib.request.urlopen": "sync HTTP inside async code; use an "
    "async client or asyncio.to_thread",
    "requests.get": "sync HTTP inside async code",
    "requests.post": "sync HTTP inside async code",
    "requests.put": "sync HTTP inside async code",
    "requests.delete": "sync HTTP inside async code",
    "requests.request": "sync HTTP inside async code",
    "socket.create_connection": "sync connect inside async code; use "
    "asyncio.open_connection",
    "socket.getaddrinfo": "sync DNS resolution inside async code; use "
    "loop.getaddrinfo",
    "select.select": "select.select blocks the loop",
}

# asyncio coroutine functions whose bare call is always a lost await
_ASYNCIO_COROS = {
    "asyncio.sleep",
    "asyncio.gather",
    "asyncio.wait",
    "asyncio.wait_for",
    "asyncio.to_thread",
    "asyncio.open_connection",
    "asyncio.start_server",
}

_TASK_SPAWNERS = ("asyncio.create_task", "asyncio.ensure_future")


def _async_defs(tree: ast.Module) -> Iterator[ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node


@rule(
    "ASY101",
    "blocking-call-in-async",
    "blocking call (time.sleep, sync I/O, subprocess) directly inside "
    "an async def starves the event loop",
)
def blocking_call_in_async(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _async_defs(ctx.tree):
        for node in walk_in_function(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in _BLOCKING_CALLS:
                out.append(
                    Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "ASY101", "blocking-call-in-async",
                        f"`{name}` inside `async def {fn.name}`: "
                        + _BLOCKING_CALLS[name],
                    )
                )
    return out


@rule(
    "ASY102",
    "unawaited-coroutine",
    "calling a coroutine function as a bare statement never runs it",
)
def unawaited_coroutine(ctx: FileContext) -> List[Finding]:
    async_names = {fn.name for fn in _async_defs(ctx.tree)}
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Call)
        ):
            continue
        call = node.value
        name = dotted(call.func)
        if name is None:
            continue
        hit = None
        if name in _ASYNCIO_COROS:
            hit = name
        elif name in async_names:
            hit = name
        elif name.count(".") == 1 and name.split(".")[0] in (
            "self", "cls"
        ):
            # exactly `self.x()` — a deeper chain (`self.pool.stop()`)
            # targets another object whose `stop` we cannot see
            attr = name.split(".")[1]
            if attr in async_names:
                hit = name
        if hit is not None:
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "ASY102", "unawaited-coroutine",
                    f"`{hit}(...)` is a coroutine call whose result is "
                    "discarded — it never runs; await it or wrap it in "
                    "asyncio.create_task",
                )
            )
    return out


@rule(
    "ASY103",
    "dropped-task",
    "asyncio.create_task result discarded: the task can be "
    "garbage-collected mid-flight and its exceptions are lost",
)
def dropped_task(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Call)
        ):
            continue
        name = dotted(node.value.func)
        if name is None:
            continue
        if name in _TASK_SPAWNERS or name.endswith(".create_task"):
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "ASY103", "dropped-task",
                    f"result of `{name}` dropped: the event loop keeps "
                    "only a weak reference — retain the task (registry "
                    "or add_done_callback) so it cannot be GC'd "
                    "mid-flight",
                )
            )
    return out


def _is_broad(handler_type: ast.AST | None) -> str | None:
    """Return the offending spelling if the except clause is broad."""
    if handler_type is None:
        return "bare except"
    name = dotted(handler_type)
    if name in ("Exception", "BaseException", "builtins.Exception",
                "builtins.BaseException"):
        return f"except {name}"
    if isinstance(handler_type, ast.Tuple):
        for el in handler_type.elts:
            broad = _is_broad(el)
            if broad is not None:
                return broad
    return None


def _mentions_cancelled(handler_type: ast.AST | None) -> bool:
    if handler_type is None:
        return False
    if isinstance(handler_type, ast.Tuple):
        return any(_mentions_cancelled(e) for e in handler_type.elts)
    name = dotted(handler_type) or ""
    return name.endswith("CancelledError")


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(
        isinstance(n, ast.Raise) for n in walk_in_function(handler)
    )


@rule(
    "ASY104",
    "broad-except-in-async",
    "broad except around awaited code can swallow cancellation and "
    "shutdown errors; catch narrowly or re-raise CancelledError first",
)
def broad_except_in_async(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _async_defs(ctx.tree):
        for node in walk_in_function(fn):
            if not isinstance(node, ast.Try):
                continue
            if not any(body_awaits(stmt) for stmt in node.body):
                continue
            cancelled_handled = False
            for handler in node.handlers:
                # A NARROW CancelledError handler means cancellation
                # was explicitly considered (re-raise, or the
                # sanctioned `except CancelledError: pass` after a
                # self-cancel); a broad handler whose tuple merely
                # names CancelledError still swallows it and stays
                # flagged.
                broad = _is_broad(handler.type)
                if _mentions_cancelled(handler.type) and broad is None:
                    cancelled_handled = True
                if (
                    broad is None
                    or cancelled_handled
                    or _reraises(handler)
                ):
                    continue
                # bare / BaseException / a tuple naming CancelledError
                # literally swallow cancellation; `except Exception`
                # does NOT on py3.8+ (CancelledError is BaseException)
                # but still hides every shutdown-adjacent error
                swallows_cancel = broad != "except Exception" or (
                    _mentions_cancelled(handler.type)
                )
                if swallows_cancel:
                    why = (
                        "swallows asyncio.CancelledError — shutdown "
                        "hangs while this handler eats the cancel"
                    )
                else:
                    why = (
                        "hides every error indiscriminately (the task "
                        "keeps running on state the failed await left "
                        "behind); catch narrowly, or add `except "
                        "asyncio.CancelledError: raise` above it to "
                        "record cancellation intent"
                    )
                out.append(
                    Finding(
                        ctx.path, handler.lineno, handler.col_offset,
                        "ASY104", "broad-except-in-async",
                        f"{broad} around awaited code in `async def "
                        f"{fn.name}` {why}",
                    )
                )
    return out


def _lockish(expr: ast.AST) -> str | None:
    name = dotted(expr)
    if name is None and isinstance(expr, ast.Call):
        name = dotted(expr.func)
    if name is None:
        return None
    low = name.lower()
    # segment match, not substring: `block_store`/`unblock` must not
    # read as locks in a blockchain codebase
    segments = [s for part in low.split(".") for s in part.split("_")]
    if (
        "lock" in segments
        or "rlock" in segments
        or "mutex" in segments
        or low.endswith(".acquire")
    ):
        return name
    return None


@rule(
    "ASY105",
    "sync-lock-across-await",
    "a threading lock held across an await point deadlocks the loop "
    "the moment a second task contends for it",
)
def sync_lock_across_await(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _async_defs(ctx.tree):
        for node in walk_in_function(fn):
            if not isinstance(node, ast.With):
                continue
            held = [
                n
                for item in node.items
                if (n := _lockish(item.context_expr)) is not None
            ]
            if not held:
                continue
            if any(body_awaits(stmt) for stmt in node.body):
                out.append(
                    Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "ASY105", "sync-lock-across-await",
                        f"`with {held[0]}` spans an await in `async def "
                        f"{fn.name}`: the loop thread parks inside the "
                        "critical section — use asyncio.Lock with "
                        "`async with`",
                    )
                )
    return out


# ABCI application-surface methods (abci/types.py Application + the
# fork's app-mempool/batch extensions): a synchronous call to any of
# these inside a reactor's receive() runs an app round-trip on the
# event loop — every peer connection stalls behind one tx.
_ABCI_SYNC_METHODS = {
    "check_tx",
    "check_tx_batch",
    "insert_tx",
    "reap_txs",
    "query",
    "info",
    "echo",
    "init_chain",
    "prepare_proposal",
    "process_proposal",
    "extend_vote",
    "verify_vote_extension",
    "finalize_block",
    "commit",
    "list_snapshots",
    "offer_snapshot",
    "load_snapshot_chunk",
    "apply_snapshot_chunk",
}

# receiver spellings that mark the call as an ABCI/mempool path
# (name-based like the other rules: `self.mempool.check_tx`,
# `self.proxy.query`, `env.proxy.mempool.check_tx`, ...)
_ABCI_RECEIVER_SEGMENTS = {"proxy", "mempool", "app", "abci", "client"}


def _reactor_classes(tree: ast.Module) -> Iterator[ast.ClassDef]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        names = [node.name] + [
            b for base in node.bases if (b := dotted(base)) is not None
        ]
        if any(n.endswith("Reactor") for n in names):
            yield node


@rule(
    "ASY108",
    "sync-abci-in-receive",
    "a synchronous ABCI proxy/mempool call inside a reactor receive() "
    "blocks the p2p event loop on an app round-trip; enqueue to the "
    "mempool ingest plane or offload via asyncio.to_thread",
)
def sync_abci_in_receive(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in _reactor_classes(ctx.tree):
        for fn in cls.body:
            # receive() is a SYNC callback by contract; an async
            # variant would be a different bug (the switch never
            # awaits it) caught by ASY102 at the call site
            if not (
                isinstance(fn, ast.FunctionDef) and fn.name == "receive"
            ):
                continue
            for node in walk_in_function(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name is None or "." not in name:
                    continue
                parts = name.split(".")
                if parts[-1] not in _ABCI_SYNC_METHODS:
                    continue
                recv_segments = {
                    s
                    for part in parts[:-1]
                    for s in part.lower().split("_")
                }
                if not recv_segments & _ABCI_RECEIVER_SEGMENTS:
                    continue
                out.append(
                    Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "ASY108", "sync-abci-in-receive",
                        f"`{name}` inside `{cls.name}.receive`: a "
                        "synchronous ABCI call on the p2p dispatch "
                        "path stalls every peer behind one app "
                        "round-trip — enqueue (mempool/ingest.py) or "
                        "offload to a thread",
                    )
                )
    return out


# hot-plane packages where an UNBOUNDED asyncio queue is a latent
# OOM + latency bomb: producers outrun a stalled consumer silently
# until the process dies. Bounded queues shed-and-count instead
# (obs/queues.py). Path prefixes, posix-style.
_HOT_PLANE_PREFIXES = (
    "cometbft_tpu/mempool/",
    "cometbft_tpu/p2p/",
    "cometbft_tpu/lp2p/",
    "cometbft_tpu/blocksync/",
    "cometbft_tpu/consensus/",
    "cometbft_tpu/rpc/",
    "cometbft_tpu/statesync/",
    "cometbft_tpu/types/",
    "cometbft_tpu/obs/",
)

# constructor spellings that create an asyncio-queue-like object
_QUEUE_CTORS = ("Queue", "LifoQueue", "PriorityQueue", "InstrumentedQueue")


def _unbounded_queue_call(node: ast.Call) -> str | None:
    """Return the offending ctor spelling if this call builds an
    unbounded asyncio queue (no maxsize, or a literal 0)."""
    name = dotted(node.func)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if last not in _QUEUE_CTORS:
        return None
    if last != "InstrumentedQueue" and not name.startswith("asyncio."):
        # only the unambiguous asyncio spelling and our own wrapper:
        # bare Queue()/LifoQueue()/PriorityQueue() could be the sync
        # queue module's (thread-safe, a different concern), and
        # queue.Queue/multiprocessing.Queue are definitely not ours
        return None
    size = None
    if node.args:
        size = node.args[0]
    for kw in node.keywords:
        if kw.arg == "maxsize":
            size = kw.value
    if size is None:
        return name
    if isinstance(size, ast.Constant) and size.value in (0, None):
        return name
    return None


@rule(
    "ASY109",
    "unbounded-queue-in-hot-plane",
    "an asyncio.Queue() with no maxsize in a hot-plane module grows "
    "without bound when its consumer stalls; bound it and shed-and-"
    "count (obs/queues.InstrumentedQueue)",
)
def unbounded_queue_in_hot_plane(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if not any(p in path for p in _HOT_PLANE_PREFIXES):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _unbounded_queue_call(node)
        if name is not None:
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "ASY109", "unbounded-queue-in-hot-plane",
                    f"`{name}(...)` without a maxsize in a hot-plane "
                    "module: a stalled consumer grows it until OOM "
                    "and every queued item adds tail latency — pass "
                    "a bound (shed-and-count under overload, "
                    "obs/queues.py)",
                )
            )
    return out


# shutdown-path function names covered by ASY110: these are the
# teardown entry points whose hang IS the wedge class (a stop chain
# awaiting a sub-plane that never returns — see obs/shutdown.py)
_STOP_NAMES = {
    "stop", "_stop", "close", "_close", "aclose", "shutdown",
    "_shutdown", "_halt", "kill", "crash",
}

# awaited spellings that are bounded by construction
_BOUNDED_AWAITS = {"asyncio.wait_for", "asyncio.sleep"}


def _stop_await_allowed(node: ast.Await) -> bool:
    """True when the awaited expression is bounded: asyncio.wait_for /
    sleep, asyncio.wait WITH a timeout, a ShutdownGuard ``.stage``
    hop, or delegation to another covered shutdown method on self/cls
    (which this rule lints on its own)."""
    value = node.value
    if not isinstance(value, ast.Call):
        return False  # bare `await task` / `await fut`: unbounded
    name = dotted(value.func)
    if name is None:
        return False
    if name in _BOUNDED_AWAITS:
        return True
    if name == "asyncio.wait":
        return any(kw.arg == "timeout" for kw in value.keywords)
    if name.endswith(".stage"):
        return True  # obs/shutdown.ShutdownGuard budgeted stage
    parts = name.split(".")
    if (
        len(parts) == 2
        and parts[0] in ("self", "cls")
        and parts[1] in _STOP_NAMES
    ):
        return True  # stop() -> self._halt(): the inner one is linted
    return False


@rule(
    "ASY110",
    "unbounded-await-in-stop",
    "an unbounded await inside a stop()/_shutdown()/close() path of a "
    "hot-plane module can wedge the whole teardown when the awaited "
    "plane hangs; bound it (asyncio.wait_for / ShutdownGuard.stage) "
    "or document the suppression",
)
def unbounded_await_in_stop(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    prefixes = _HOT_PLANE_PREFIXES + (
        "cometbft_tpu/node/",
        "cometbft_tpu/chaos/",
    )
    if not any(p in path for p in prefixes):
        return []
    out: List[Finding] = []
    for fn in _async_defs(ctx.tree):
        if fn.name not in _STOP_NAMES:
            continue
        for node in walk_in_function(fn):
            if not isinstance(node, ast.Await):
                continue
            if _stop_await_allowed(node):
                continue
            what = (
                dotted(node.value.func)
                if isinstance(node.value, ast.Call)
                else None
            )
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "ASY110", "unbounded-await-in-stop",
                    f"unbounded `await {what or '<expr>'}` in shutdown "
                    f"path `async def {fn.name}`: if the awaited plane "
                    "hangs, teardown wedges with the loop alive and "
                    "store fds open — wrap in asyncio.wait_for (or a "
                    "ShutdownGuard.stage with a budget), or suppress "
                    "with a comment documenting why it cannot hang",
                )
            )
    return out


# The ONLY sanctioned direct-fsync site in the hot planes: the WAL's
# group-commit seam (consensus/wal.py flush_sync + repair paths),
# where barriers coalesce and the disk stall runs off-loop. A direct
# os.fsync anywhere else in a hot plane is a serial disk stall the
# seam exists to absorb — and on the consensus loop it parks every
# peer at once.
_FSYNC_SEAM_FILES = ("cometbft_tpu/consensus/wal.py",)


@rule(
    "ASY111",
    "direct-fsync-in-hot-plane",
    "a direct os.fsync in a hot-plane module outside the WAL "
    "group-commit seam is a serial disk stall on a latency-critical "
    "path; route the barrier through consensus/wal.py (write_sync / "
    "write_group) or move it off-plane",
)
def direct_fsync_in_hot_plane(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if not any(p in path for p in _HOT_PLANE_PREFIXES):
        return []
    if any(seam in path for seam in _FSYNC_SEAM_FILES):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if dotted(node.func) != "os.fsync":
            continue
        out.append(
            Finding(
                ctx.path, node.lineno, node.col_offset,
                "ASY111", "direct-fsync-in-hot-plane",
                "`os.fsync` in a hot-plane module outside the WAL "
                "group-commit seam: each call is a serial disk "
                "barrier on a latency-critical path (and a loop "
                "stall when called from the consensus/p2p loop) — "
                "write through consensus/wal.py's write_sync/"
                "write_group seam, or move the fsync off-plane",
            )
        )
    return out


# Reconnect paths live in the p2p planes (both switch flavors).
_RECONNECT_PREFIXES = ("cometbft_tpu/p2p/", "cometbft_tpu/lp2p/")


def _awaits_dial(loop_node: ast.AST) -> bool:
    """True when the loop body awaits a dial-ish call (last dotted
    segment contains "dial": dial, dial_peer, _try_dial, redial)."""
    for n in walk_in_function(loop_node):
        if not (
            isinstance(n, ast.Await) and isinstance(n.value, ast.Call)
        ):
            continue
        name = dotted(n.value.func)
        if name is not None and "dial" in name.rsplit(".", 1)[-1]:
            return True
    return False


def _finite_loop(node: ast.AST) -> str | None:
    """The offending spelling if this loop runs a FINITE attempt
    schedule: ``for ... in range(...)`` or ``while <counter compare>``
    (``while True`` is unbounded and fine)."""
    if isinstance(node, (ast.For, ast.AsyncFor)):
        it = node.iter
        if isinstance(it, ast.Call) and dotted(it.func) == "range":
            return "for ... in range(...)"
        return None
    if isinstance(node, ast.While) and isinstance(node.test, ast.Compare):
        return "while <attempt bound>"
    return None


@rule(
    "ASY112",
    "finite-reconnect-give-up",
    "a bounded attempt loop around a p2p dial that abandons a "
    "persistent peer when the budget runs out: a healed partition can "
    "then never re-converge — hand the peer to the reconnect plane's "
    "slow lane instead (p2p/reconnect.py)",
)
def finite_reconnect_give_up(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if not any(p in path for p in _RECONNECT_PREFIXES):
        return []
    out: List[Finding] = []
    for fn in _async_defs(ctx.tree):
        # a slow-lane handoff anywhere in the function means the
        # budget is a LANE TRANSITION, not a give-up — the exact
        # pattern the reconnect plane's fast lane uses
        hands_off = any(
            isinstance(n, ast.Call)
            and "slow_lane" in (dotted(n.func) or "")
            for n in walk_in_function(fn)
        )
        if hands_off:
            continue
        for node in walk_in_function(fn):
            spelling = _finite_loop(node)
            if spelling is None or not _awaits_dial(node):
                continue
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "ASY112", "finite-reconnect-give-up",
                    f"`{spelling}` dial loop in `async def {fn.name}` "
                    "gives up on the peer when the budget runs out — "
                    "a healed partition minority then stays isolated "
                    "FOREVER (the liveness hole the chaos matrix "
                    "found); park the peer in the reconnect plane's "
                    "slow lane (never-give-up sweep) when the fast "
                    "budget is spent",
                )
            )
    return out


@rule(
    "ASY106",
    "nested-event-loop",
    "asyncio.run / run_until_complete inside an async def always "
    "raises or deadlocks: a loop is already running on this thread",
)
def nested_event_loop(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _async_defs(ctx.tree):
        for node in walk_in_function(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            if name == "asyncio.run" or name.endswith(
                ".run_until_complete"
            ):
                out.append(
                    Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "ASY106", "nested-event-loop",
                        f"`{name}` inside `async def {fn.name}`: a "
                        "loop is already running — await the coroutine "
                        "directly",
                    )
                )
    return out


# the commit-verify entry points that must ride the shared serving
# seam when called from light/ (ASY113): signature work here fans out
# per SESSION, so a bare call re-pays crypto a thousand times over
_LIGHT_VERIFY_NAMES = {
    "verify_commit",
    "verify_commit_light",
    "verify_commit_light_trusting",
    "verify_commits_coalesced",
    "verify_commit_jobs_coalesced",
}

_LIGHT_PKG = "cometbft_tpu/light/"


@rule(
    "ASY113",
    "uncoalesced-verify-in-light",
    "a commit signature verification in light/ that bypasses the "
    "shared cache / coalesce seam: per-request crypto multiplies by "
    "the session count on the serving plane (light/serving.py)",
)
def uncoalesced_verify_in_light(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if _LIGHT_PKG not in path and not path.startswith("light/"):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        parts = name.split(".")
        if parts[-1] not in _LIGHT_VERIFY_NAMES:
            continue
        # calls ON the coalescing engine ARE the seam (the engine
        # owns the shared cache + batch window)
        if any("engine" in p for p in parts[:-1]):
            continue
        if any(
            kw.arg in ("cache", "engine") and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None
            )
            for kw in node.keywords
        ):
            continue
        out.append(
            Finding(
                ctx.path, node.lineno, node.col_offset,
                "ASY113", "uncoalesced-verify-in-light",
                f"`{name}` in light/ verifies per-request, bypassing "
                "the shared cache/coalesce seam — pass the shared "
                "SignatureCache (cache=...) or route through the "
                "serving plane's CoalescedCommitVerifier "
                "(light/serving.py): on the serving plane this "
                "crypto multiplies by the session count",
            )
        )
    return out


# Storage-plane packages where a scan-driven delete loop is the
# crash-consistency + latency hazard ASY120 targets (the hot planes
# plus the stores the retention plane prunes).
_ASY120_PREFIXES = _HOT_PLANE_PREFIXES + (
    "cometbft_tpu/store/",
    "cometbft_tpu/state/",
    "cometbft_tpu/evidence/",
    "cometbft_tpu/light/",
)

# iterator spellings that walk a DB keyspace: a loop over one of
# these has data-dependent (unbounded) trip count by construction
_DB_SCAN_NAMES = {"iter_prefix", "iter_range", "iter_all"}


def _scan_driven(iter_expr: ast.expr) -> str | None:
    """The scan spelling when ``for ... in <iter_expr>`` walks a DB
    keyspace (directly, or through list()/sorted()/enumerate())."""
    for node in ast.walk(iter_expr):
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            last = name.rsplit(".", 1)[-1]
            if last in _DB_SCAN_NAMES:
                return name
    return None


@rule(
    "ASY120",
    "unbounded-delete-in-hot-plane",
    "a DB-scan loop issuing one-at-a-time .delete() calls in a "
    "storage/hot-plane module: unbounded trip count, and a crash "
    "mid-loop leaves partial deletes with no base marker — "
    "accumulate and commit ONE atomic write_batch (deletes + marker "
    "advance together), sliced in bounded steps (store/retention.py)",
)
def unbounded_delete_in_hot_plane(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if not any(p in path for p in _ASY120_PREFIXES):
        return []
    out: List[Finding] = []
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, ast.For):
            continue
        scan = _scan_driven(loop.iter)
        if scan is None:
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if not name.endswith(".delete"):
                continue
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "ASY120", "unbounded-delete-in-hot-plane",
                    f"`{name}(...)` inside a loop over `{scan}`: the "
                    "scan's trip count is data-dependent (every row "
                    "under the prefix) and each delete is an "
                    "independent write — a crash mid-loop strands "
                    "partial deletes with no marker recording how far "
                    "it got, and the store lock is held for the whole "
                    "scan. Collect doomed keys, then commit deletes + "
                    "base-marker advance in ONE bounded write_batch "
                    "(the store/retention.py slicing discipline)",
                )
            )
    return out


# Verify-consumer planes that must dispatch signature batches through
# the unified scheduler (crypto/scheduler.py) rather than building
# their own BatchVerifier / reaching the parallel-verify pool
# directly: a bypass verifies OUTSIDE the priority classes, so a
# catch-up storm it spawns can starve the live round the scheduler
# exists to protect (ASY121). The sanctioned seams are crypto/ itself
# and types/validation (the choke point every plane submits through).
_ASY121_PREFIXES = (
    "cometbft_tpu/consensus/",
    "cometbft_tpu/blocksync/",
    "cometbft_tpu/light/",
    "cometbft_tpu/statesync/",
    "cometbft_tpu/evidence/",
)

# direct-construction spellings of the batch-verifier backends plus
# the factory; any of these in a hot plane is an unscheduled verify
_ASY121_CTORS = {
    "CpuBatchVerifier",
    "CpuParallelBatchVerifier",
    "TpuBatchVerifier",
    "MeshBatchVerifier",
    "create_batch_verifier",
}


@rule(
    "ASY121",
    "verify-bypass-scheduler",
    "a hot-plane module (consensus/blocksync/light/statesync/"
    "evidence) constructing a BatchVerifier or reaching the "
    "parallel-verify pool directly: signature work dispatched outside "
    "the unified scheduler's priority classes can starve the live "
    "round — submit through crypto/scheduler.py (the types/validation "
    "seam does this for every commit-verify entry point)",
)
def verify_bypass_scheduler(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if not any(p in path for p in _ASY121_PREFIXES):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        parts = name.split(".")
        offending = None
        if parts[-1] in _ASY121_CTORS:
            offending = parts[-1]
        elif "parallel_verify" in parts[:-1]:
            # parallel_verify.engine() / .dispatch_stats_if_running()
            # etc: stats reads are harmless but verification through
            # the raw pool bypasses the classes — route the batch via
            # the scheduler and read stats through obs/queues.py
            if not parts[-1].endswith("_if_running"):
                offending = name
        if offending is None:
            continue
        out.append(
            Finding(
                ctx.path, node.lineno, node.col_offset,
                "ASY121", "verify-bypass-scheduler",
                f"`{name}(...)` verifies outside the unified "
                "scheduler: this plane's batches must submit through "
                "crypto/scheduler.py (priority class "
                "live/light/catchup) or the types/validation seam — "
                "a direct backend verify here shares no queue with "
                "the live round and can starve it",
            )
        )
    return out


# Fleet code that serves a request off a replica's serving plane
# without going through SessionRouter admission (ASY122): the router
# is the ONE seam that holds the fleet's invariants — gate admission
# (counted sheds, bounded waits), consistency tokens (never serve
# below the token), lag-aware degradation and failover accounting. A
# direct plane call from fleet/ code serves unadmitted, untokened and
# uncounted. The sanctioned module is router.py itself; plane
# lifecycle calls (drain/resume/stats/register_queues) are not
# serving and stay clean.
_ASY122_PREFIX = "cometbft_tpu/fleet/"
_ASY122_ROUTER_SEAM = "router.py"

# serving entry points on the plane/cache/session objects; "serve" is
# matched only through an explicit plane receiver so unrelated
# `.serve()` spellings elsewhere in fleet code don't false-positive
_ASY122_SERVE_CALLS = {"open_session", "verified_block", "get_or_verify"}


@rule(
    "ASY122",
    "serve-bypass-router",
    "fleet/ code reaching a replica's serving plane directly "
    "(open_session / verified_block / get_or_verify / "
    "light_plane.serve) instead of going through SessionRouter "
    "admission: a bypass serves unadmitted (no gate, no counted "
    "shed), untokened (can serve below a consistency token) and "
    "invisible to lag degradation/failover — route through "
    "router.serve_light / route_light / subscribe",
)
def serve_bypass_router(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if _ASY122_PREFIX not in path or path.endswith(
        "/" + _ASY122_ROUTER_SEAM
    ):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        parts = name.split(".")
        offending = None
        if parts[-1] in _ASY122_SERVE_CALLS:
            offending = parts[-1]
        elif parts[-1] == "serve" and any(
            "plane" in p for p in parts[:-1]
        ):
            offending = name
        if offending is None:
            continue
        out.append(
            Finding(
                ctx.path, node.lineno, node.col_offset,
                "ASY122", "serve-bypass-router",
                f"`{name}(...)` reaches the serving plane without "
                "SessionRouter admission: fleet code must serve "
                "through the router seam (serve_light / route_light "
                "/ subscribe) so the request is gate-admitted, "
                "token-checked and counted by lag/failover "
                "accounting",
            )
        )
    return out
