"""Finalize-path batching rule (whole-program pass).

ASY123 guards the native finalize lane (state/native_finalize.py):
once the per-block hash/encode work is batched into ONE GIL-releasing
native pass, any NEW Python ``for``-loop (or comprehension) that
hashes or encodes per item on a finalize-reachable call path quietly
reintroduces the host overhead the lane removed — and, on the
pipelined path, work that no longer releases the GIL while riding
``asyncio.to_thread``. The sanctioned shape is the batch seam itself:
``native_finalize.finalize_pass`` / ``merkle.hash_from_byte_slices``
(both route native and are excluded below), with downstream consumers
reading the precomputed ``FinalizeArtifacts`` instead of re-deriving.

Portable FALLBACK loops (the no-compiler twin, replay/compat decode
paths) are real and allowed — suppress their loop lines with a
justified ``# bftlint: disable=ASY123 — ...`` comment, the same
sanctioned-sink contract as ASY114/ASY116.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..astutil import dotted
from ..callgraph import Project, walk_with_lambdas
from ..findings import Finding
from ..registry import project_rule

# the finalize phases (state/execution.py) — BFS roots; everything
# they reach synchronously runs per committed block
_FINALIZE_ROOTS = {
    "apply_block",
    "apply_verified_block",
    "apply_finalize",
    "apply_hash_persist",
    "apply_complete",
}

# where a per-item hash/encode loop on the finalize path is THIS
# rule's bug class (the state plane owns the finalize data path)
_ASY123_PREFIXES = ("cometbft_tpu/state/",)

# the sanctioned batch seams: they ARE the native lane (portable
# twins included — differential tests pin them byte-identical)
_SEAM_PATHS = (
    "state/native_finalize.py",
    "crypto/merkle.py",
    "utils/wirecodec.py",
)

# hash/encode leaves by call spelling (last dotted component)
_HASH_ENC_LEAVES = {
    "sha256": "hashes per item",
    "leaf_hash": "leaf-hashes per item",
    "inner_hash": "hashes per item",
    "_enc_abci_event": "encodes an ABCI event per item",
    "_enc_tx_result": "encodes a tx result per item",
    "attr_kvi": "flattens event attributes per item",
}

_ASY123_MAX_DEPTH = 8


def _target_names(t: ast.AST) -> set:
    return {
        n.id for n in ast.walk(t) if isinstance(n, ast.Name)
    }


def _loop_regions(fn_node) -> Iterator[Tuple[ast.AST, set, list, str]]:
    """(anchor, loop-var names, body nodes, kind) per loop/comp."""
    for node in walk_with_lambdas(fn_node):
        if isinstance(node, ast.For):
            body = []
            for stmt in node.body:
                body.append(stmt)
                body.extend(walk_with_lambdas(stmt))
            yield node, _target_names(node.target), body, "for-loop"
        elif isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                   ast.DictComp)
        ):
            names: set = set()
            for gen in node.generators:
                names |= _target_names(gen.target)
            elts = (
                [node.key, node.value]
                if isinstance(node, ast.DictComp)
                else [node.elt]
            )
            body = []
            for e in elts:
                body.append(e)
                body.extend(walk_with_lambdas(e))
            yield node, names, body, "comprehension"


def _per_item_calls(fn) -> Iterator[Tuple[ast.Call, str, str, str]]:
    """(call, spelling, why, kind) for hash/encode work done per
    iterated item: a known leaf called in a loop body, or
    ``<loopvar>.encode()`` (the per-result proto encode pattern —
    receiver-checked so ordinary ``str.encode`` on non-items stays
    out)."""
    for _, names, body, kind in _loop_regions(fn.node):
        for node in body:
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            last = name.rsplit(".", 1)[-1]
            why = _HASH_ENC_LEAVES.get(last)
            if why is None and last == "encode":
                root = name.split(".", 1)[0]
                if root in names:
                    why = "proto-encodes per item"
            if why is not None:
                yield node, name, why, kind


@project_rule(
    "ASY123",
    "per-item-hash-in-finalize-path",
    "a Python for-loop/comprehension hashes or encodes per item on a "
    "finalize-reachable call path: the native finalize lane "
    "(state/native_finalize.py) batches exactly this work into one "
    "GIL-releasing pass per block — thread its FinalizeArtifacts "
    "through instead, or justify the loop line (portable fallbacks)",
)
def per_item_hash_in_finalize_path(project: Project) -> List[Finding]:
    # BFS the synchronous call tree from the finalize phase roots
    roots = [
        fi
        for fi in project.functions.values()
        if fi.name in _FINALIZE_ROOTS
        and any(p in fi.path.replace("\\", "/") for p in _ASY123_PREFIXES)
    ]
    reach = {}  # qualname -> (root name, chain of call spellings)
    queue = []
    for r in roots:
        if r.qualname not in reach:
            reach[r.qualname] = (r.name, ())
            queue.append((r, 0))
    while queue:
        fn, depth = queue.pop(0)
        if depth >= _ASY123_MAX_DEPTH:
            continue
        root, chain = reach[fn.qualname]
        for cs in fn.calls:
            callee = project.functions.get(cs.callee)
            if callee is None or callee.qualname in reach:
                continue
            reach[callee.qualname] = (root, chain + (cs.spelling,))
            queue.append((callee, depth + 1))

    out: List[Finding] = []
    seen = set()
    for qual in sorted(reach):
        fi = project.functions.get(qual)
        if fi is None:
            continue
        p = fi.path.replace("\\", "/")
        if not any(pref in p for pref in _ASY123_PREFIXES):
            continue  # reached code outside the state plane: not ours
        if any(seam in p for seam in _SEAM_PATHS):
            continue  # the sanctioned batch seam itself
        root, chain = reach[qual]
        for call, name, why, kind in _per_item_calls(fi):
            if project._suppressed(fi.path, call.lineno, "ASY123"):
                continue
            key = (fi.path, call.lineno, name)
            if key in seen:
                continue
            seen.add(key)
            via = (
                " via " + " -> ".join(f"`{c}`" for c in chain)
                if chain
                else ""
            )
            out.append(
                Finding(
                    fi.path, call.lineno, call.col_offset,
                    "ASY123", "per-item-hash-in-finalize-path",
                    f"`{name}` {why} inside a {kind} in `{fi.name}`, "
                    f"reached from finalize root `{root}`{via} — this "
                    "runs per committed block on the apply path; "
                    "batch it through the native finalize lane "
                    "(state/native_finalize.finalize_pass artifacts) "
                    "or justify the line as a portable fallback",
                    chain=(root,) + chain + (fi.name,),
                )
            )
    return sorted(out)
