"""JAX hot-path hygiene rules (JAX2xx).

Scoped to code that runs under `jax.jit` (detected via decorator or
the `return jax.jit(core)` factory idiom).  The failure class is
silent: a stray `.item()` or per-call `jax.jit(...)` wrapper doesn't
crash, it just turns a 60k-sig/s Ed25519 verify batch into a
host-synced crawl (cf. arxiv 2302.00418 on EdDSA batch verification
throughput in committee-based consensus).
"""
from __future__ import annotations

import ast
from typing import List

from ..astutil import (
    dotted,
    jitted_functions,
    param_names,
    root_name,
)
from ..findings import Finding
from ..registry import FileContext, rule

_HOST_MATERIALIZERS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array", "jax.device_get",
}

_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_static_operand(node: ast.AST) -> bool:
    """int(x.shape[0])-style casts touch static metadata, not traced
    values — they are jit-safe."""
    if isinstance(node, ast.Constant):
        return True
    return any(
        isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS
        for n in ast.walk(node)
    )


@rule(
    "JAX201",
    "host-sync-in-jit",
    ".item()/float()/np.asarray on a traced value forces a device→host "
    "sync (or a trace error) inside a jitted function",
)
def host_sync_in_jit(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in jitted_functions(ctx.tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            msg = None
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                msg = "`.item()` forces a device→host sync"
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and not _is_static_operand(node.args[0])
            ):
                msg = (
                    f"`{node.func.id}()` on a traced value syncs to "
                    "host (or raises TracerConversionError)"
                )
            else:
                name = dotted(node.func)
                if name in _HOST_MATERIALIZERS:
                    msg = f"`{name}` materializes on host"
            if msg is not None:
                out.append(
                    Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "JAX201", "host-sync-in-jit",
                        f"{msg} inside jitted `{fn.name}` — keep the "
                        "hot path on-device (jnp ops) and sync only at "
                        "designated points",
                    )
                )
    return out


@rule(
    "JAX202",
    "stray-block-until-ready",
    "block_until_ready outside a designated sync point serializes "
    "dispatch against the device",
)
def stray_block_until_ready(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "JAX202", "stray-block-until-ready",
                    "`.block_until_ready()` stalls the dispatch "
                    "pipeline; restrict to designated sync points and "
                    "mark those `# bftlint: disable=JAX202` with a "
                    "justification",
                )
            )
    return out


_STATIC_ITERATORS = {"range", "reversed"}
_WRAPPING_ITERATORS = {"enumerate", "zip"}


@rule(
    "JAX203",
    "traced-loop",
    "a Python for-loop over a traced array unrolls at trace time or "
    "raises; use jax.lax.scan / fori_loop or vectorize",
)
def traced_loop(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in jitted_functions(ctx.tree):
        params = param_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.For):
                continue
            it = node.iter
            hit = None
            if isinstance(it, ast.Name) and it.id in params:
                hit = it.id
            elif isinstance(it, ast.Call):
                fname = dotted(it.func)
                if fname in _STATIC_ITERATORS:
                    continue
                if fname in _WRAPPING_ITERATORS:
                    for arg in it.args:
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in params
                        ):
                            hit = arg.id
                            break
            if hit is not None:
                out.append(
                    Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "JAX203", "traced-loop",
                        f"Python loop over parameter `{hit}` of jitted "
                        f"`{fn.name}`: unrolls per-element at trace "
                        "time — use jax.lax.scan/fori_loop or jnp "
                        "vector ops",
                    )
                )
    return out


@rule(
    "JAX204",
    "per-call-jit",
    "jax.jit applied per call (immediately invoked or inside a loop) "
    "defeats the compile cache and recompiles every time",
)
def per_call_jit(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    # jit-calls already reported by the wrap-and-invoke branch on
    # their enclosing Call: skip them in the loop branch so
    # `for ...: jax.jit(g)(x)` reports once, not twice
    invoked: set = set()

    def visit(node: ast.AST, loop_depth: int) -> None:
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname and (fname == "jit" or fname.endswith(".jit")):
                why = None
                if loop_depth > 0 and id(node) not in invoked:
                    why = (
                        "called inside a loop: each iteration builds a "
                        "fresh wrapper with an empty compile cache"
                    )
                if why is not None:
                    out.append(
                        Finding(
                            ctx.path, node.lineno, node.col_offset,
                            "JAX204", "per-call-jit",
                            f"`{fname}(...)` {why} — hoist the jitted "
                            "callable out of the hot path",
                        )
                    )
            # jax.jit(f)(x): the jit call is the func of an outer call
            inner = node.func
            if isinstance(inner, ast.Call):
                iname = dotted(inner.func)
                if iname and (
                    iname == "jit" or iname.endswith(".jit")
                ):
                    invoked.add(id(inner))
                    out.append(
                        Finding(
                            ctx.path, node.lineno, node.col_offset,
                            "JAX204", "per-call-jit",
                            f"`{iname}(f)(...)` wraps and invokes in "
                            "one expression: the wrapper (and its "
                            "compile cache) dies with the statement — "
                            "bind the jitted callable once",
                        )
                    )
        entering_loop = isinstance(node, (ast.For, ast.While))
        for child in ast.iter_child_nodes(node):
            visit(child, loop_depth + (1 if entering_loop else 0))

    visit(ctx.tree, 0)
    return out
