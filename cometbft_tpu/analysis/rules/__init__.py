"""Built-in bftlint rules; importing this package registers them."""
from . import async_rules, jax_rules, trace_rules  # noqa: F401
