"""Built-in bftlint rules; importing this package registers them."""
from . import (  # noqa: F401
    async_rules,
    complexity_rules,
    finalize_rules,
    interproc_rules,
    jax_rules,
    trace_rules,
)
