"""Committee-scale complexity rules (whole-program pass).

Built on analysis/complexity.py's loop-domain dataflow: every loop
gets an iteration domain (validators, peers, subscribers, heights,
txs), and committee-domain loops propagate interprocedurally. The
bug class is ROADMAP item 1's: at 100+ validators any O(validators)
work on a per-message path is O(V^2) per height, because the number
of messages per height is itself O(V).

- **ASY117 superlinear-msg-handler** — a validators/peers-domain
  loop reachable from a per-message hot-plane handler (receive,
  ``_handle_msg``, vote/part submit, gossip send routines). The
  finding carries BOTH the call chain and the domain-inference
  chain, so a reviewer can audit each hop.
- **ASY118 nested-committee-loop** — committee x committee nesting
  (validator x validator, peer x validator) in consensus/p2p/types:
  the direct quadratic, same-function or through a call inside the
  outer loop.
- **ASY119 unbounded-growth-in-hot-plane** — a dict/list/set
  attribute in a hot plane with reachable adds but NO reachable
  prune/pop/clear anywhere in the tree: the leak class ROADMAP item
  5's months-horizon soak needs killed.

Suppressing a flagged LOOP line in its own file sanctions it for
ASY117/ASY118 chains (one justified comment kills the whole fan of
chain findings — the ASY114 sanctioned-sink contract). The
suppression-hygiene test requires every such comment to carry a
justification.
"""
from __future__ import annotations

from typing import List

from ..callgraph import Project
from ..complexity import (
    COMMITTEE_DOMAINS,
    collect_growable_attrs,
    collect_pruned_attrs,
    model_for,
    reachable_from,
    render_chain,
    render_trace,
)
from ..findings import Finding
from ..registry import project_rule
from .async_rules import _HOT_PLANE_PREFIXES
from .interproc_rules import _in_scope

# per-message handlers + gossip send routines: the entry points whose
# work is multiplied by O(V) messages per height
_HANDLER_NAMES = {
    "receive",
    "_handle_msg",
    "_on_peer_msg",
    "_on_stream",
    "_submit_vote",
    "_on_cs_broadcast",
    "_on_event",
    "_on_publish",
    "broadcast",
    "_broadcast",
    "_gossip_routine",
    "_broadcast_tx_routine",
}

# where committee x committee nesting is the direct quadratic
_ASY118_PREFIXES = (
    "cometbft_tpu/consensus/",
    "cometbft_tpu/p2p/",
    "cometbft_tpu/lp2p/",
    "cometbft_tpu/types/",
)


def _is_handler(fi) -> bool:
    return fi.name in _HANDLER_NAMES and _in_scope(
        fi.path, _HOT_PLANE_PREFIXES
    )


@project_rule(
    "ASY117",
    "superlinear-msg-handler",
    "a validators/peers-domain loop is reachable from a per-message "
    "hot-plane handler: O(V) work per message times O(V) messages "
    "per height is O(V^2) — make the work incremental "
    "(cursor/index/memo) or justify the loop line",
)
def superlinear_msg_handler(project: Project) -> List[Finding]:
    model = model_for(project)
    out: List[Finding] = []
    seen = set()  # (handler_qual, loop path, loop line) dedup
    for qual in sorted(project.functions):
        fi = project.functions[qual]
        if not _is_handler(fi):
            continue
        s = model.summary(qual)
        for dl in s.committee_loops:
            if project._suppressed(fi.path, dl.line, "ASY117"):
                continue
            key = (qual, fi.path, dl.line)
            if key in seen:
                continue
            seen.add(key)
            out.append(
                Finding(
                    fi.path, dl.line, dl.col,
                    "ASY117", "superlinear-msg-handler",
                    f"per-message handler `{fi.name}` runs a "
                    f"{dl.domain}-domain {dl.kind} over "
                    f"`{dl.spelling}` inline — O({dl.domain}) work "
                    "per message with O(validators) messages per "
                    "height is O(V^2); make it incremental "
                    "(cursor/index/memo) "
                    f"[domain: {render_trace(dl.trace)}]",
                    chain=(fi.name,),
                    domain_trace=dl.trace,
                )
            )
        for cs in fi.calls:
            callee = project.functions.get(cs.callee)
            if callee is None:
                continue
            if callee.is_async and not cs.awaited:
                continue
            if _is_handler(callee):
                continue  # charged to the nearer handler
            hit = model.committee_chain(
                cs.callee, "ASY117", skip=_is_handler
            )
            if hit is None:
                continue
            key = (qual, hit.path, hit.loop.line)
            if key in seen:
                continue
            seen.add(key)
            chain = (cs.spelling,) + hit.chain
            out.append(
                Finding(
                    fi.path, cs.line, cs.col,
                    "ASY117", "superlinear-msg-handler",
                    f"per-message handler `{fi.name}` reaches a "
                    f"{hit.loop.domain}-domain loop: "
                    f"{render_chain(fi.name, chain, hit)} — "
                    f"O({hit.loop.domain}) work per message with "
                    "O(validators) messages per height is O(V^2); "
                    "make the reached work incremental or justify "
                    "the loop line "
                    f"[domain: {render_trace(hit.loop.trace)}]",
                    chain=(fi.name,) + chain,
                    domain_trace=hit.loop.trace,
                )
            )
    return out


@project_rule(
    "ASY118",
    "nested-committee-loop",
    "committee x committee loop nesting (validator x validator, "
    "peer x validator) in consensus/p2p/types — the direct "
    "quadratic; hoist the inner scan into an index built once "
    "outside the loop",
)
def nested_committee_loop(project: Project) -> List[Finding]:
    model = model_for(project)
    out: List[Finding] = []
    for qual in sorted(project.functions):
        fi = project.functions[qual]
        if not _in_scope(fi.path, _ASY118_PREFIXES):
            continue
        s = model.summary(qual)
        for outer, inner in s.nested:
            if project._suppressed(fi.path, inner.line, "ASY118"):
                continue
            out.append(
                Finding(
                    fi.path, inner.line, inner.col,
                    "ASY118", "nested-committee-loop",
                    f"{inner.domain}-domain {inner.kind} over "
                    f"`{inner.spelling}` nested inside a "
                    f"{outer.domain}-domain loop over "
                    f"`{outer.spelling}` (line {outer.line}) in "
                    f"`{fi.name}`: O({outer.domain} x "
                    f"{inner.domain}) — build an index/dict once "
                    "outside the outer loop and look up inside "
                    f"[domain: {render_trace(inner.trace)}]",
                    chain=(fi.name,),
                    domain_trace=inner.trace,
                )
            )
        for cil in s.calls_in_loops:
            callee = project.functions.get(cil.site.callee)
            if callee is None:
                continue
            if callee.is_async and not cil.site.awaited:
                continue
            hit = model.committee_chain(cil.site.callee, "ASY118")
            if hit is None:
                continue
            if project._suppressed(
                fi.path, cil.site.line, "ASY118"
            ):
                continue
            out.append(
                Finding(
                    fi.path, cil.site.line, cil.site.col,
                    "ASY118", "nested-committee-loop",
                    f"`{cil.site.spelling}(...)` called inside a "
                    f"{cil.loop.domain}-domain loop over "
                    f"`{cil.loop.spelling}` (line {cil.loop.line}) "
                    f"reaches a {hit.loop.domain}-domain loop: "
                    f"{render_chain(fi.name, (cil.site.spelling,) + hit.chain, hit)}"
                    f" — O({cil.loop.domain} x {hit.loop.domain}); "
                    "hoist the inner scan or make the callee "
                    "incremental "
                    f"[domain: {render_trace(hit.loop.trace)}]",
                    chain=(fi.name, cil.site.spelling) + hit.chain,
                    domain_trace=hit.loop.trace,
                )
            )
    return out


@project_rule(
    "ASY119",
    "unbounded-growth-in-hot-plane",
    "a dict/list/set attribute in a hot plane has reachable adds "
    "but no reachable prune/pop/clear/LRU anywhere in the tree — "
    "unbounded on the months-horizon soak; bound it or justify the "
    "init line",
)
def unbounded_growth_in_hot_plane(project: Project) -> List[Finding]:
    pruned = collect_pruned_attrs(project)
    # only adds on the per-message closure count: a container grown
    # at registration/startup time scales with config, not traffic
    hot = reachable_from(
        project,
        (fi for fi in project.functions.values() if _is_handler(fi)),
    )
    out: List[Finding] = []
    growable = collect_growable_attrs(
        project, lambda p: _in_scope(p, _HOT_PLANE_PREFIXES)
    )
    for ga in growable:
        if ga.attr in pruned:
            continue
        grows = [g for g in ga.grows if g.func_qual in hot]
        if not grows:
            continue
        if project._suppressed(ga.path, ga.line, "ASY119"):
            continue
        sites = ", ".join(
            f"{g.path.rsplit('/', 1)[-1]}:{g.line} `{g.op}`"
            for g in grows[:3]
        )
        more = (
            f" (+{len(grows) - 3} more)" if len(grows) > 3 else ""
        )
        out.append(
            Finding(
                ga.path, ga.line, ga.col,
                "ASY119", "unbounded-growth-in-hot-plane",
                f"`{ga.class_name}.{ga.attr}` ({ga.kind}) grows on "
                f"the per-message plane at {sites}{more} with no "
                "reachable prune/pop/clear/LRU anywhere in the tree "
                "— unbounded growth under traffic; bound it "
                "(high-water prune, LRU, per-height drop) or "
                "justify this init line",
                chain=(ga.class_name,),
                domain_trace=tuple(
                    f"{g.path}:{g.line} `{g.op}`" for g in grows
                ),
            )
        )
    return sorted(out)
