"""Tracing-plane hygiene rules (ASY107).

The trace subsystem's whole value is trustworthy latency math: span
durations are differences of ``time.monotonic_ns`` readings. A
wall-clock read (``time.time`` / ``time.time_ns`` / ``datetime.now``)
anywhere in the plane silently breaks that — an NTP step or DST jump
mid-span yields negative or wildly wrong durations that poison the
p99s *and* the span→metrics bridge. The rule hard-forbids wall-clock
call spellings in ``cometbft_tpu/trace/``; code that genuinely needs
a wall anchor must take it from the caller, outside the plane.
"""
from __future__ import annotations

import ast
from typing import List

from ..astutil import dotted
from ..findings import Finding
from ..registry import FileContext, rule

_WALLCLOCK = {
    "time.time",
    "time.time_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
}

_TRACE_PKG = "cometbft_tpu/trace/"


@rule(
    "ASY107",
    "wallclock-in-trace",
    "wall-clock reads inside the tracing plane break span math "
    "(NTP steps make durations negative); use time.monotonic_ns",
)
def wallclock_in_trace(ctx: FileContext) -> List[Finding]:
    path = ctx.path.replace("\\", "/")
    if _TRACE_PKG not in path and not path.startswith("trace/"):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name in _WALLCLOCK:
            out.append(
                Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "ASY107", "wallclock-in-trace",
                    f"`{name}` inside the tracing plane: span "
                    "timestamps must be monotonic "
                    "(time.monotonic_ns) — wall clock steps corrupt "
                    "durations and the span→metrics bridge",
                )
            )
    return out
