"""Shared AST helpers for bftlint rules (stdlib ``ast`` only)."""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an Attribute/Subscript/Starred/Call chain."""
    while True:
        if isinstance(node, (ast.Attribute, ast.Starred)):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def walk_in_function(node: ast.AST) -> Iterator[ast.AST]:
    """Like ast.walk over a function body, but does not descend into
    nested function/class definitions (their bodies run in a different
    execution context)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(
            child,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.Lambda),
        ):
            continue
        stack.extend(ast.iter_child_nodes(child))


def body_awaits(node: ast.AST) -> bool:
    """True if executing this node can hit an await / async-for /
    async-with in the *same* function (nested defs excluded)."""
    return any(
        isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
        for n in walk_in_function(node)
    )


def functions_with_async_context(
    tree: ast.Module,
) -> Iterator[ast.AsyncFunctionDef]:
    """Every async def in the module, at any nesting depth."""
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node


def _decorator_is_jit(dec: ast.AST) -> bool:
    name = dotted(dec)
    if name is not None:
        return name == "jit" or name.endswith(".jit")
    if isinstance(dec, ast.Call):
        fname = dotted(dec.func)
        if fname is None:
            return False
        if fname == "jit" or fname.endswith(".jit"):
            return True  # @jax.jit(...) / @partial-free call form
        if fname in ("partial", "functools.partial") and dec.args:
            return _decorator_is_jit(dec.args[0])
    return False


def jitted_functions(tree: ast.Module) -> List[ast.FunctionDef]:
    """Functions that run under jit: either decorated with (a partial
    of) ``jit``, or later wrapped via ``jax.jit(fn)`` anywhere in the
    module (the ``return jax.jit(core)`` factory idiom)."""
    wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname and (fname == "jit" or fname.endswith(".jit")):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        wrapped.add(arg.id)
    out: List[ast.FunctionDef] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name in wrapped or any(
            _decorator_is_jit(d) for d in node.decorator_list
        ):
            out.append(node)
    return out


def param_names(fn: ast.FunctionDef) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)
