"""Loop-domain dataflow over the callgraph.Project model.

Every ``for``/``async for``/comprehension gets an ITERATION DOMAIN —
what the loop is O(...) in — inferred from the iterable expression:

- spelling tables: ``valset.validators``, ``self.peers.values()``,
  ``commit.signatures`` all spell a committee-scale domain;
- element-type annotations: a ``Sequence[Validator]`` parameter is
  validators-domain wherever it is iterated;
- wrapper unwrapping: ``zip()``, ``enumerate()``, ``sorted()``,
  ``reversed()``, ``range(len(x))`` / ``range(x.size())`` and
  ``.values()/.items()/.keys()`` are transparent — the domain is the
  wrapped iterable's (the exact vote-loop shapes that previously
  evaded inference, see docs/LINT.md);
- local dataflow: ``updates = [c for c in changes if ...]`` inherits
  the domain of ``changes``;
- attribute types: the PR 14 inferred attribute types name the
  receiver class in the trace (``self.val_set`` is a ValidatorSet).

Domains propagate INTERPROCEDURALLY: a committee-domain loop in a
callee is charged to every caller chain that reaches it — sync calls
always, async calls only when awaited at the site (a spawned task is
not per-message work). The model feeds three project rules
(rules/complexity_rules.py: ASY117/ASY118/ASY119) and names the call
sites the empirical probe (analysis/scaling.py) drives.

Pure stdlib, like the rest of the analysis plane.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .astutil import dotted
from .callgraph import CallSite, FunctionInfo, Project, walk_with_lambdas

# --- domains -----------------------------------------------------------

VALIDATORS = "validators"
PEERS = "peers"
SUBSCRIBERS = "subscribers"
HEIGHTS = "heights"
TXS = "txs"

#: the committee-scale domains: O(these) per message is O(V^2) per
#: height once every validator sends (ROADMAP item 1's blowup class)
COMMITTEE_DOMAINS = (VALIDATORS, PEERS)

# final name segment -> domain. A spelling match is evidence by
# convention: the tree consistently names validator-indexed lanes
# (votes, signatures) and peer tables this way.
_SPELLINGS: Dict[str, str] = {
    "validators": VALIDATORS,
    "votes": VALIDATORS,
    "votes_by_index": VALIDATORS,
    "signatures": VALIDATORS,
    "extended_signatures": VALIDATORS,
    "commit_sigs": VALIDATORS,
    "peers": PEERS,
    "peer_states": PEERS,
    "subscribers": SUBSCRIBERS,
    "members": SUBSCRIBERS,
    "sessions": SUBSCRIBERS,
    "waiters": SUBSCRIBERS,
    "heights": HEIGHTS,
    "txs": TXS,
}

# element-type annotation -> domain (``Sequence[Validator]``,
# ``Dict[int, Vote]``, ``List[Peer]`` parameters)
_ELEM_TYPES: Dict[str, str] = {
    "Validator": VALIDATORS,
    "Vote": VALIDATORS,
    "CommitSig": VALIDATORS,
    "ExtendedCommitSig": VALIDATORS,
    "Peer": PEERS,
    "FanoutSubscriber": SUBSCRIBERS,
}

# receiver class whose .size()/len() counts committee members:
# ``range(vs.size())`` iterates the validators domain
_SIZED_TYPES: Dict[str, str] = {
    "ValidatorSet": VALIDATORS,
    "VoteSet": VALIDATORS,
}

# calls transparent to the iteration domain (the satellite gap fix:
# zip/enumerate destructuring used to evade inference entirely)
_UNWRAP_CALLS = {
    "zip", "enumerate", "sorted", "list", "set", "tuple",
    "frozenset", "reversed", "iter",
}
# methods transparent to the iteration domain
_UNWRAP_METHODS = {"values", "items", "keys", "copy"}


@dataclass(frozen=True)
class DomainHit:
    """One classified iterable: the domain plus the inference steps
    that led there (rendered into ASY117/118 messages)."""

    domain: str
    spelling: str
    trace: Tuple[str, ...]


@dataclass(frozen=True)
class DomainLoop:
    """One loop/comprehension whose iterable classified."""

    domain: str
    line: int
    col: int
    spelling: str
    kind: str  # "for" | "async for" | "comprehension"
    trace: Tuple[str, ...]


@dataclass
class CallInLoop:
    """A resolved call site lexically inside a committee-domain
    loop: the edge ASY118's interprocedural half walks."""

    site: CallSite
    loop: DomainLoop


@dataclass
class FuncSummary:
    fi: FunctionInfo
    loops: List[DomainLoop] = field(default_factory=list)
    nested: List[Tuple[DomainLoop, DomainLoop]] = field(
        default_factory=list
    )  # (outer, inner) committee x committee, same function
    calls_in_loops: List[CallInLoop] = field(default_factory=list)

    @property
    def committee_loops(self) -> List[DomainLoop]:
        return [l for l in self.loops if l.domain in COMMITTEE_DOMAINS]


@dataclass(frozen=True)
class ChainHit:
    """Nearest reachable committee loop + the call chain to it."""

    loop: DomainLoop
    path: str  # file containing the loop
    func_name: str  # function containing the loop
    chain: Tuple[str, ...]  # call spellings walked (may be empty)


# --- iterable classification ------------------------------------------


class _Classifier:
    """Domain classification for one function's expressions."""

    def __init__(self, project: Project, fi: FunctionInfo):
        self.project = project
        self.fi = fi
        self.local_types = project._local_var_types(fi)
        self.env: Dict[str, DomainHit] = self._param_domains()
        self._fold_local_assignments()

    # parameters: by spelling or by element-type annotation
    def _param_domains(self) -> Dict[str, DomainHit]:
        out: Dict[str, DomainHit] = {}
        a = self.fi.node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.arg in _SPELLINGS:
                d = _SPELLINGS[p.arg]
                out[p.arg] = DomainHit(
                    d, p.arg,
                    (f"parameter `{p.arg}` spells the {d} domain",),
                )
                continue
            d = _ann_elem_domain(p.annotation)
            if d is not None:
                out[p.arg] = DomainHit(
                    d, p.arg,
                    (f"parameter `{p.arg}` is annotated with "
                     f"{d}-domain elements",),
                )
        return out

    def _fold_local_assignments(self) -> None:
        """``updates = [c for c in changes ...]`` inherits the domain
        of ``changes``. Two passes so chained assignments resolve
        regardless of walk order."""
        for _ in range(2):
            changed = False
            for node in walk_with_lambdas(self.fi.node):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    continue
                name = node.targets[0].id
                if name in self.env:
                    continue
                hit = self.classify(node.value)
                if hit is not None:
                    self.env[name] = DomainHit(
                        hit.domain, name,
                        hit.trace + (f"assigned to `{name}`",),
                    )
                    changed = True
            if not changed:
                break

    def _type_of(self, expr) -> Optional[str]:
        """Inferred class name of a dotted expression (PR 14 attribute
        types + annotated/constructed locals)."""
        name = dotted(expr)
        if name is None:
            return None
        parts = name.split(".")
        if parts[0] in ("self", "cls"):
            ci = self.project._class_of(self.fi)
            t: Optional[str] = None
            for seg in parts[1:]:
                if ci is None:
                    return None
                t = ci.attr_types.get(seg)
                ci = (
                    self.project._resolve_class(ci.path, t)
                    if t else None
                )
            return t
        t = self.local_types.get(parts[0])
        for seg in parts[1:]:
            ci = (
                self.project._resolve_class(self.fi.path, t)
                if t else None
            )
            if ci is None:
                return None
            t = ci.attr_types.get(seg)
        return t

    def classify(self, expr) -> Optional[DomainHit]:
        return self._classify(expr, ())

    def _classify(self, expr, trace) -> Optional[DomainHit]:
        if isinstance(expr, ast.Call):
            return self._classify_call(expr, trace)
        if isinstance(expr, ast.Attribute):
            if expr.attr in _SPELLINGS:
                d = _SPELLINGS[expr.attr]
                spelling = dotted(expr) or expr.attr
                recv_t = self._type_of(expr.value)
                step = f"`{spelling}` spells the {d} domain"
                if recv_t is not None:
                    step += f" (receiver resolves to {recv_t})"
                return DomainHit(d, spelling, trace + (step,))
            return None
        if isinstance(expr, ast.Name):
            hit = self.env.get(expr.id)
            if hit is not None:
                return DomainHit(
                    hit.domain, expr.id, trace + hit.trace
                )
            if expr.id in _SPELLINGS:
                d = _SPELLINGS[expr.id]
                return DomainHit(
                    d, expr.id,
                    trace + (f"`{expr.id}` spells the {d} domain",),
                )
            return None
        if isinstance(expr, ast.Subscript):
            # a slice of a committee lane is still the committee lane
            # (``self.validators[1:]``); a single index is not
            if isinstance(expr.slice, ast.Slice):
                return self._classify(
                    expr.value, trace + ("unwrap slice",)
                )
            return None
        if isinstance(expr, ast.Starred):
            return self._classify(expr.value, trace)
        if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, ast.Add
        ):
            return (
                self._classify(expr.left, trace)
                or self._classify(expr.right, trace)
            )
        if isinstance(expr, ast.IfExp):
            return (
                self._classify(expr.body, trace)
                or self._classify(expr.orelse, trace)
            )
        if isinstance(
            expr,
            (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp),
        ):
            # the comprehension's cardinality is its first
            # generator's (filters only shrink it)
            if expr.generators:
                return self._classify(
                    expr.generators[0].iter,
                    trace + ("via comprehension",),
                )
            return None
        return None

    def _classify_call(self, expr: ast.Call, trace):
        fname = dotted(expr.func)
        base = fname.rsplit(".", 1)[-1] if fname else None
        if base in _UNWRAP_CALLS and expr.args:
            step = trace + (f"unwrap `{base}(...)`",)
            for a in expr.args:
                hit = self._classify(a, step)
                if hit is not None:
                    return hit
            return None
        if base == "range" and len(expr.args) == 1:
            a = expr.args[0]
            if isinstance(a, ast.Call):
                g = dotted(a.func)
                gb = g.rsplit(".", 1)[-1] if g else None
                if gb == "len" and a.args:
                    return self._classify(
                        a.args[0],
                        trace + ("unwrap `range(len(...))`",),
                    )
                if gb in ("size", "__len__") and isinstance(
                    a.func, ast.Attribute
                ):
                    recv = a.func.value
                    t = self._type_of(recv)
                    if t in _SIZED_TYPES:
                        d = _SIZED_TYPES[t]
                        spelling = dotted(recv) or "<recv>"
                        return DomainHit(
                            d, spelling,
                            trace + (
                                f"`range({spelling}.{gb}())` counts "
                                f"a {t}: the {d} domain",
                            ),
                        )
            return None
        if (
            base in _UNWRAP_METHODS
            and isinstance(expr.func, ast.Attribute)
            and not expr.args
        ):
            return self._classify(
                expr.func.value, trace + (f"unwrap `.{base}()`",)
            )
        return None


def _ann_elem_domain(ann) -> Optional[str]:
    """Element domain of an annotation: any identifier inside it
    (``Sequence[Validator]``, ``Dict[int, Vote]``, ``"List[Peer]"``)
    that names a committee element type."""
    if ann is None:
        return None
    for n in ast.walk(ann):
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            # string annotation: cheap split, not a parse
            for tok in (
                n.value.replace("[", " ").replace("]", " ")
                .replace(",", " ").split()
            ):
                t = tok.rsplit(".", 1)[-1]
                if t in _ELEM_TYPES:
                    return _ELEM_TYPES[t]
        if name in _ELEM_TYPES:
            return _ELEM_TYPES[name]
    return None


# --- per-function summaries -------------------------------------------


def _innermost_committee(stack: List[DomainLoop]) -> Optional[DomainLoop]:
    for dl in reversed(stack):
        if dl.domain in COMMITTEE_DOMAINS:
            return dl
    return None


def summarize(project: Project, fi: FunctionInfo) -> FuncSummary:
    """Walk one function body tracking the loop stack; nested defs
    are skipped (they summarize separately), lambdas are inline."""
    cls = _Classifier(project, fi)
    out = FuncSummary(fi)
    by_pos: Dict[Tuple[int, int], CallSite] = {}
    for cs in fi.calls:
        by_pos.setdefault((cs.line, cs.col), cs)

    def add_loop(iter_expr, node, kind, stack) -> Optional[DomainLoop]:
        hit = cls.classify(iter_expr)
        if hit is None:
            return None
        dl = DomainLoop(
            hit.domain, node.lineno, node.col_offset,
            hit.spelling, kind, hit.trace,
        )
        out.loops.append(dl)
        if dl.domain in COMMITTEE_DOMAINS:
            outer = _innermost_committee(stack)
            if outer is not None:
                out.nested.append((outer, dl))
        return dl

    def visit(node, stack) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            visit(node.iter, stack)
            kind = (
                "async for" if isinstance(node, ast.AsyncFor) else "for"
            )
            dl = add_loop(node.iter, node, kind, stack)
            inner = stack + [dl] if dl is not None else stack
            for n in [node.target] + node.body + node.orelse:
                visit(n, inner)
            return
        if isinstance(
            node,
            (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
        ):
            st = stack
            for gen in node.generators:
                visit(gen.iter, st)
                dl = add_loop(gen.iter, node, "comprehension", st)
                if dl is not None:
                    st = st + [dl]
                for cond in gen.ifs:
                    visit(cond, st)
            if isinstance(node, ast.DictComp):
                visit(node.key, st)
                visit(node.value, st)
            else:
                visit(node.elt, st)
            return
        if isinstance(node, ast.Call):
            cs = by_pos.get((node.lineno, node.col_offset))
            loop = _innermost_committee(stack)
            if cs is not None and loop is not None:
                out.calls_in_loops.append(CallInLoop(cs, loop))
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    for stmt in fi.node.body:
        visit(stmt, [])
    return out


# --- the whole-program model ------------------------------------------

_MAX_CHAIN_DEPTH = 8  # same audit bound as ASY116


class ComplexityModel:
    """Lazy per-function summaries + interprocedural committee-loop
    reachability, cached on the Project instance (ASY117 and ASY118
    share one model per engine run)."""

    def __init__(self, project: Project):
        self.project = project
        self._summaries: Dict[str, FuncSummary] = {}

    def summary(self, qualname: str) -> Optional[FuncSummary]:
        s = self._summaries.get(qualname)
        if s is None:
            fi = self.project.functions.get(qualname)
            if fi is None:
                return None
            s = summarize(self.project, fi)
            self._summaries[qualname] = s
        return s

    def committee_chain(
        self,
        qualname: str,
        rule_id: str,
        skip=None,
    ) -> Optional[ChainHit]:
        """BFS from ``qualname`` (inclusive) to the nearest
        committee-domain loop. Sync callees always count; async
        callees only when awaited at the site (spawned work is not
        per-message). Loops suppressed for ``rule_id`` in their own
        file are sanctioned sinks — chains through them vanish, one
        justified comment kills the whole fan (the ASY114 escape-
        hatch contract)."""
        fi0 = self.project.functions.get(qualname)
        if fi0 is None:
            return None
        seen: Set[str] = {qualname}
        queue: List[Tuple[FunctionInfo, Tuple[str, ...], int]] = [
            (fi0, (), 0)
        ]
        while queue:
            fi, chain, depth = queue.pop(0)
            s = self.summary(fi.qualname)
            for dl in s.committee_loops:
                if self.project._suppressed(fi.path, dl.line, rule_id):
                    continue
                return ChainHit(dl, fi.path, fi.name, chain)
            if depth >= _MAX_CHAIN_DEPTH:
                continue
            for cs in fi.calls:
                callee = self.project.functions.get(cs.callee)
                if callee is None or callee.qualname in seen:
                    continue
                if callee.is_async and not cs.awaited:
                    continue
                if skip is not None and skip(callee):
                    continue
                seen.add(callee.qualname)
                queue.append(
                    (callee, chain + (cs.spelling,), depth + 1)
                )
        return None


def reachable_from(project: Project, roots) -> Set[str]:
    """Qualnames reachable from ``roots`` (inclusive) through sync
    calls and awaited async calls, bounded at _MAX_CHAIN_DEPTH — the
    per-message closure ASY119 scopes grow sites to."""
    seen: Set[str] = set()
    queue: List[Tuple[FunctionInfo, int]] = []
    for fi in roots:
        if fi.qualname not in seen:
            seen.add(fi.qualname)
            queue.append((fi, 0))
    while queue:
        fi, depth = queue.pop(0)
        if depth >= _MAX_CHAIN_DEPTH:
            continue
        for cs in fi.calls:
            callee = project.functions.get(cs.callee)
            if callee is None or callee.qualname in seen:
                continue
            if callee.is_async and not cs.awaited:
                continue
            seen.add(callee.qualname)
            queue.append((callee, depth + 1))
    return seen


def model_for(project: Project) -> ComplexityModel:
    m = getattr(project, "_complexity_model", None)
    if m is None:
        m = ComplexityModel(project)
        project._complexity_model = m
    return m


# --- unbounded-growth analysis (ASY119's engine) ----------------------

_GROW_METHODS = {
    "append", "add", "appendleft", "insert", "setdefault",
    "extend", "update",
}
_PRUNE_METHODS = {
    "pop", "popitem", "remove", "discard", "clear", "popleft",
}


def _empty_container(expr) -> Optional[str]:
    """Container kind when ``expr`` initializes an EMPTY growable
    container (``{}``, ``[]``, ``set()``, ``deque()`` without
    maxlen, ``field(default_factory=dict)``), else None."""
    if isinstance(expr, ast.Dict) and not expr.keys:
        return "dict"
    if isinstance(expr, ast.List) and not expr.elts:
        return "list"
    if isinstance(expr, ast.Call):
        f = dotted(expr.func)
        base = f.rsplit(".", 1)[-1] if f else None
        if base in ("dict", "list", "set", "OrderedDict"):
            if not expr.args and not expr.keywords:
                return base
        if base == "defaultdict" and not any(
            kw.arg == "maxlen" for kw in expr.keywords
        ):
            return "defaultdict"
        if base == "deque" and not any(
            kw.arg == "maxlen" for kw in expr.keywords
        ):
            return "deque"
        if base == "field":
            for kw in expr.keywords:
                if kw.arg == "default_factory":
                    n = dotted(kw.value)
                    nb = n.rsplit(".", 1)[-1] if n else None
                    if nb in (
                        "dict", "list", "set", "OrderedDict", "deque"
                    ):
                        return nb
    return None


@dataclass(frozen=True)
class GrowthSite:
    path: str
    line: int
    op: str  # ".append", "[k] =", ...
    func_qual: str  # qualname of the method containing the add


@dataclass
class GrowableAttr:
    class_name: str
    attr: str
    kind: str  # container kind
    path: str
    line: int  # the init site (where the finding lands)
    col: int
    grows: List[GrowthSite] = field(default_factory=list)


def _attr_of_target(expr) -> Optional[str]:
    """Attribute name for ``<recv>.x`` shapes, any receiver."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def collect_pruned_attrs(project: Project) -> Set[str]:
    """Attribute names with ANY reachable shrink anywhere in the
    tree: ``<recv>.x.pop(...)``, ``del <recv>.x[...]``, slice
    rewrite, or reassignment outside an ``__init__``. Name-based on
    purpose — cross-object prunes (a reactor clearing a peer-state
    map) must count, and an under-approximated GROW with an over-
    approximated PRUNE keeps ASY119's false-positive rate down."""
    pruned: Set[str] = set()
    for fi in project.functions.values():
        in_init = fi.name == "__init__"
        # local aliases of attributes: ``fifo = self._durable_fifo``
        # followed by ``fifo.pop(0)`` prunes the attribute
        aliases: Dict[str, str] = {}
        for node in walk_with_lambdas(fi.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Attribute)
            ):
                aliases[node.targets[0].id] = node.value.attr
        for node in walk_with_lambdas(fi.node):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr in _PRUNE_METHODS:
                    recv = node.func.value
                    a = _attr_of_target(recv)
                    if a is None and isinstance(recv, ast.Name):
                        a = aliases.get(recv.id)
                    if a is not None:
                        pruned.add(a)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    v = t.value if isinstance(t, ast.Subscript) else t
                    a = _attr_of_target(v)
                    if a is not None:
                        pruned.add(a)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Slice)
                    ):
                        a = _attr_of_target(t.value)
                        if a is not None:
                            pruned.add(a)  # x[:] = ... rewrite
                    elif not in_init:
                        a = _attr_of_target(t)
                        if a is not None:
                            pruned.add(a)  # reassignment resets it
    return pruned


def collect_growable_attrs(
    project: Project, path_filter
) -> List[GrowableAttr]:
    """Per class (in paths accepted by ``path_filter``): attributes
    initialized as empty containers in ``__init__``/class body, with
    the grow sites reachable through the class's own methods."""
    out: List[GrowableAttr] = []
    for path, classes in sorted(project.module_classes.items()):
        if not path_filter(path):
            continue
        for ci in classes.values():
            attrs: Dict[str, GrowableAttr] = {}
            # class-body fields (dataclass field defaults / shared
            # class-level containers)
            for stmt in ci.node.body:
                target = value = None
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    target, value = stmt.target.id, stmt.value
                elif isinstance(stmt, ast.Assign) and len(
                    stmt.targets
                ) == 1 and isinstance(stmt.targets[0], ast.Name):
                    target, value = stmt.targets[0].id, stmt.value
                if target is None or value is None:
                    continue
                kind = _empty_container(value)
                if kind is not None:
                    attrs[target] = GrowableAttr(
                        ci.name, target, kind, path,
                        stmt.lineno, stmt.col_offset,
                    )
            init = ci.methods.get("__init__")
            if init is not None:
                for node in walk_with_lambdas(init.node):
                    # both `self.x = {}` and `self.x: Dict[...] = {}`
                    if isinstance(node, ast.Assign) and len(
                        node.targets
                    ) == 1:
                        t = node.targets[0]
                    elif isinstance(node, ast.AnnAssign):
                        t = node.target
                    else:
                        continue
                    if node.value is None:
                        continue
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    kind = _empty_container(node.value)
                    if kind is not None:
                        attrs[t.attr] = GrowableAttr(
                            ci.name, t.attr, kind, path,
                            node.lineno, node.col_offset,
                        )
            if not attrs:
                continue
            for mname, m in ci.methods.items():
                if mname == "__init__":
                    continue
                for node in walk_with_lambdas(m.node):
                    if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute
                    ):
                        if node.func.attr not in _GROW_METHODS:
                            continue
                        recv = node.func.value
                        if (
                            isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == "self"
                            and recv.attr in attrs
                        ):
                            attrs[recv.attr].grows.append(
                                GrowthSite(
                                    m.path, node.lineno,
                                    f".{node.func.attr}",
                                    m.qualname,
                                )
                            )
                    elif isinstance(node, (ast.Assign, ast.AugAssign)):
                        targets = (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                        for t in targets:
                            if not (
                                isinstance(t, ast.Subscript)
                                and not isinstance(t.slice, ast.Slice)
                            ):
                                continue
                            recv = t.value
                            if (
                                isinstance(recv, ast.Attribute)
                                and isinstance(recv.value, ast.Name)
                                and recv.value.id == "self"
                                and recv.attr in attrs
                            ):
                                attrs[recv.attr].grows.append(
                                    GrowthSite(
                                        m.path, node.lineno, "[k] =",
                                        m.qualname,
                                    )
                                )
            out.extend(
                a for _, a in sorted(attrs.items()) if a.grows
            )
    return out


def render_trace(trace: Tuple[str, ...]) -> str:
    return " ; ".join(trace)


def render_chain(
    handler: str, chain: Tuple[str, ...], hit: ChainHit
) -> str:
    steps = [f"`{handler}`"] + [f"`{c}`" for c in chain]
    loc = f"{hit.path}:{hit.loop.line}"
    steps.append(
        f"{hit.loop.kind} over `{hit.loop.spelling}` at {loc}"
    )
    return " -> ".join(steps)
