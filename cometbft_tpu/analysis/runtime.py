"""Runtime concurrency sanitizer — the dynamic half of the plane.

bftlint (the static half) proves what it can from shape; this module
watches what actually happens, the way upstream CometBFT leans on
Go's race detector in CI. Three cooperating guards, one per-process
singleton (``get_sanitizer()``), enabled via ``[instrumentation]
sanitizer`` (default ON in chaos/tests via ``config.test_config`` and
the chaos net; a production node keeps it off):

- **lock-order graph** (``sanitized_lock``): hot-plane locks are
  wrapped at construction time; every acquire records "held A while
  acquiring B" edges keyed by lock NAME (lockdep-style lock classes,
  so an ABBA inversion across two *instances* of the same pair of
  planes still counts — that interleaving is one scheduler decision
  away). A new edge that closes a cycle is a deadlock-potential
  finding carrying BOTH acquisition stacks. Single-threaded
  sequential inversions count too: the graph records ORDER, not
  contention, which is what makes the chaos ``lock_inversion``
  injection deterministic from one seed line.
- **loop-affinity guard** (``tag``/``touch``/``handoff``): hot-plane
  objects that are loop-affine by design (consensus state, mempool
  pool, the switch peer map) are tagged with their owning thread;
  a touch from a foreign thread without a sanctioned ``handoff``
  context is a finding with the offending stack. This is the
  cross-thread-mutation bug class (PR 7's zombie conns, PR 10's
  tracemalloc leak) that neither the static pass nor span data sees.
- **stall attribution** (``attribute_frames``): buckets a
  LoopWatchdog flight-record's loop stack by owning subsystem (the
  innermost frame that lives in a known plane package), so a stall
  names the guilty plane, not just a raw stack.

Disabled mode is free by construction: ``sanitized_lock`` returns
the raw lock unchanged, ``touch`` is one attribute check, and
nothing else runs. Findings ride the chaos pipeline as
invariant-style violations (chaos/net.run_schedule drains the
singleton per run), so the 50+-scenario matrix hunts races for free.

Pure stdlib; importing this module must never pull in jax.
"""
from __future__ import annotations

import contextlib
import threading
import traceback
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

_STACK_LIMIT = 16
_MAX_FINDINGS = 128

# subsystem buckets for stall attribution, matched against the
# directory component of a flight-record frame ("wal.py:254 write"
# frames carry "consensus/wal.py" once obs/watchdog keeps the parent
# dir; bare basenames fall back to the basename table below)
_PLANES = (
    "consensus", "mempool", "p2p", "lp2p", "blocksync", "statesync",
    "rpc", "light", "evidence", "abci", "crypto", "store", "state",
    "chaos", "obs", "trace", "types", "node", "e2e", "privval",
    "utils",
)


class SanitizerFinding:
    """One runtime violation: deadlock potential or affinity breach."""

    __slots__ = ("kind", "message", "detail")

    def __init__(self, kind: str, message: str, detail: dict):
        self.kind = kind
        self.message = message
        self.detail = detail

    def render(self) -> str:
        return f"sanitizer[{self.kind}]: {self.message}"

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "message": self.message,
            "detail": self.detail,
        }


def _stack(skip: int = 2) -> List[str]:
    """Compact acquisition stack: innermost-last 'file.py:ln func'."""
    out = []
    for fr in traceback.extract_stack(limit=_STACK_LIMIT + skip)[:-skip]:
        fname = fr.filename.replace("\\", "/")
        parts = fname.rsplit("/", 2)
        short = "/".join(parts[-2:]) if len(parts) > 1 else fname
        out.append(f"{short}:{fr.lineno} {fr.name}")
    return out


class _TLS(threading.local):
    def __init__(self):
        self.held: List[str] = []  # lock names, outermost first
        self.handoffs: Set[str] = set()


class ConcurrencySanitizer:
    """Per-process lock-order + loop-affinity sanitizer (module doc).

    All mutable state is guarded by one internal lock; the internal
    lock is never held while calling out, so the sanitizer itself
    cannot deadlock the planes it watches."""

    def __init__(self) -> None:
        self.enabled = False
        self._mu = threading.Lock()
        self._tls = _TLS()
        # (held, acquiring) -> first-seen stacks for both sides
        self._edges: Dict[Tuple[str, str], dict] = {}
        self._cycles_seen: Set[frozenset] = set()
        self._affinity: Dict[str, dict] = {}  # name -> owner record
        self._affinity_seen: Set[Tuple[str, str]] = set()
        self.findings: "deque[SanitizerFinding]" = deque(
            maxlen=_MAX_FINDINGS
        )
        self.lock_acquires = 0

    # --- lifecycle ----------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Fresh graph + findings + affinity tags (chaos runs isolate
        per schedule; planes re-tag at their next start, adopt-on-
        first-use owners re-adopt on the run's own thread)."""
        with self._mu:
            self._edges.clear()
            self._cycles_seen.clear()
            self._affinity.clear()
            self._affinity_seen.clear()
            self.findings.clear()

    # --- lock-order graph ---------------------------------------------

    def note_acquire(self, name: str) -> None:
        """Record edges held->name, detect a fresh cycle, push name
        onto this thread's held stack. The fast path (nothing else
        held, or all edges already known) never takes the internal
        mutex: dict reads and the counters are GIL-atomic enough for
        diagnostics; only a NEW edge pays for the lock + stack
        capture + cycle check."""
        tls = self._tls
        held = tls.held
        self.lock_acquires += 1
        if name in held:  # reentrant (RLock): no self-edges
            held.append(name)
            return
        if held:
            for h in held:
                if h == name:
                    continue
                edge = self._edges.get((h, name))
                if edge is not None:
                    edge["count"] += 1
                    continue
                acq_stack = _stack(skip=3)
                with self._mu:
                    if (h, name) in self._edges:
                        self._edges[(h, name)]["count"] += 1
                        continue
                    self._edges[(h, name)] = {
                        "holder": h,
                        "acquirer": name,
                        "stack": acq_stack,
                        "thread": threading.current_thread().name,
                        "count": 1,
                    }
                    self._check_cycle_locked(h, name)
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._tls.held
        # remove the LAST occurrence (release order can interleave)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def _check_cycle_locked(self, src: str, dst: str) -> None:
        """The new edge src->dst closes a cycle iff dst already
        reaches src. DFS over the (small) edge set; report once per
        distinct lock set, with both first-acquisition stacks."""
        path = self._find_path_locked(dst, src)
        if path is None:
            return
        cycle_nodes = frozenset(path + [dst])
        if cycle_nodes in self._cycles_seen:
            return
        self._cycles_seen.add(cycle_nodes)
        fwd = self._edges[(src, dst)]
        # the reverse direction's first edge (dst -> path[1] ... src)
        rev_key = (dst, path[1]) if len(path) > 1 else (dst, src)
        rev = self._edges.get(rev_key, {})
        order = " -> ".join(path + [dst])
        self.findings.append(
            SanitizerFinding(
                "lock-order-cycle",
                f"lock-order inversion: held `{src}` while acquiring "
                f"`{dst}`, but the reverse order `{order}` was also "
                "observed — a deadlock is one unlucky interleaving "
                "away",
                {
                    "locks": sorted(cycle_nodes),
                    "edge": f"{src}->{dst}",
                    "reverse": order,
                    "stack_forward": fwd.get("stack", []),
                    "thread_forward": fwd.get("thread", ""),
                    "stack_reverse": rev.get("stack", []),
                    "thread_reverse": rev.get("thread", ""),
                },
            )
        )

    def _find_path_locked(
        self, start: str, goal: str
    ) -> Optional[List[str]]:
        stack = [(start, [start])]
        seen = {start}
        adj: Dict[str, List[str]] = {}
        for (a, b) in self._edges:
            adj.setdefault(a, []).append(b)
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # --- loop-affinity guard ------------------------------------------

    def tag(self, name: str, owner_ident: Optional[int] = None) -> None:
        """Tag (or re-bind) a hot-plane object as affine to the
        calling (or given) thread — typically called from the plane's
        start() on its event loop."""
        ident = owner_ident or threading.get_ident()
        owner = threading.current_thread().name
        with self._mu:
            self._affinity[name] = {"ident": ident, "name": owner}

    def touch(self, name: str) -> None:
        """Assert the caller is the tagged owner thread (or inside a
        sanctioned handoff). Hot-path contract: callers pre-check
        ``sanitizer.enabled`` so the disabled cost is one attribute
        read."""
        if not self.enabled:
            return
        rec = self._affinity.get(name)
        if rec is None or rec["ident"] == threading.get_ident():
            return
        if name in self._tls.handoffs:
            return
        thread = threading.current_thread().name
        key = (name, thread)
        with self._mu:
            if key in self._affinity_seen:
                return
            self._affinity_seen.add(key)
            self.findings.append(
                SanitizerFinding(
                    "loop-affinity",
                    f"`{name}` (affine to thread "
                    f"`{rec['name']}`) touched from foreign thread "
                    f"`{thread}` without a sanctioned handoff — "
                    "cross-thread mutation of a loop-affine object "
                    "races the event loop",
                    {
                        "object": name,
                        "owner": rec["name"],
                        "thread": thread,
                        "stack": _stack(skip=2),
                    },
                )
            )

    def touch_adopt(self, name: str) -> None:
        """``touch`` with adopt-on-first-use: the first toucher
        becomes the owner (for planes with no explicit start() to tag
        from — the mempool pool's owner is whoever runs commit).
        The adopt is check-then-act under the mutex so two threads
        racing the first touch cannot BOTH adopt (one wins the tag,
        the loser falls through to a real touch and gets flagged)."""
        if not self.enabled:
            return
        adopted = False
        if name not in self._affinity:
            with self._mu:
                if name not in self._affinity:
                    self._affinity[name] = {
                        "ident": threading.get_ident(),
                        "name": threading.current_thread().name,
                    }
                    adopted = True
        if not adopted:
            self.touch(name)

    @contextlib.contextmanager
    def handoff(self, name: str):
        """Mark the calling thread as a SANCTIONED foreign toucher of
        ``name`` for the duration (the executor-drain / recheck-worker
        seams that are cross-thread by design, behind the object's own
        lock)."""
        tls = self._tls
        fresh = name not in tls.handoffs
        if fresh:
            tls.handoffs.add(name)
        try:
            yield
        finally:
            if fresh:
                tls.handoffs.discard(name)

    # --- introspection ------------------------------------------------

    def snapshot(self) -> List[dict]:
        with self._mu:
            return [f.to_json() for f in self.findings]

    def stats(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "lock_acquires": self.lock_acquires,
                "edges": len(self._edges),
                "tagged": sorted(self._affinity),
                "findings": len(self.findings),
            }


class SanitizedLock:
    """Proxy over a threading.Lock/RLock feeding the order graph.

    Forwards the Condition protocol (``_is_owned`` /
    ``_release_save`` / ``_acquire_restore``) so
    ``threading.Condition(sanitized_lock(...))`` keeps exact RLock
    semantics — and keeps the held-stack honest across a
    ``Condition.wait`` (the wait releases the lock; so does the
    bookkeeping)."""

    __slots__ = ("_san", "_lock", "name")

    def __init__(self, san: ConcurrencySanitizer, lock, name: str):
        self._san = san
        self._lock = lock
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._san.note_acquire(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        self._san.note_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    # Condition protocol (threading.Condition probes these)
    def _is_owned(self):
        inner = getattr(self._lock, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _release_save(self):
        inner = getattr(self._lock, "_release_save", None)
        state = inner() if inner is not None else self._lock.release()
        self._san.note_release(self.name)
        return state

    def _acquire_restore(self, state) -> None:
        inner = getattr(self._lock, "_acquire_restore", None)
        if inner is not None:
            inner(state)
        else:
            self._lock.acquire()
        self._san.note_acquire(self.name)

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name} {self._lock!r}>"


# --- process-wide singleton + convenience seams ------------------------

_SANITIZER = ConcurrencySanitizer()


def get_sanitizer() -> ConcurrencySanitizer:
    return _SANITIZER


def enable() -> ConcurrencySanitizer:
    _SANITIZER.enable()
    return _SANITIZER


def disable() -> None:
    _SANITIZER.disable()


def sanitized_lock(lock, name: str):
    """Wrap ``lock`` for the order graph — construction-time decision:
    with the sanitizer disabled the RAW lock comes back, so disabled
    mode costs literally nothing per acquire. Planes call this where
    they build their locks; enablement (node build / chaos / tests)
    happens before plane construction."""
    if not _SANITIZER.enabled:
        return lock
    return SanitizedLock(_SANITIZER, lock, name)


# --- stall attribution -------------------------------------------------

def attribute_frames(frames: List[str]) -> str:
    """Owning subsystem for a flight-record stack (innermost-first
    "dir/file.py:ln func" lines): the innermost frame that lives in a
    known plane package names the guilty subsystem."""
    for line in frames:
        head = line.split(":", 1)[0]
        parts = head.replace("\\", "/").split("/")
        if len(parts) >= 2 and parts[-2] in _PLANES:
            return parts[-2]
        stem = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
        if stem in _PLANES:
            return stem
    return "unknown"


def attribute_stall(record: dict) -> str:
    """Subsystem bucket for one LoopWatchdog flight record."""
    return attribute_frames(record.get("loop_stack", []))


# --- chaos injection ---------------------------------------------------

def inject_lock_inversion() -> dict:
    """Deterministically exercise BOTH guards (the chaos
    ``lock_inversion`` nemesis action): acquire two sanitizer-named
    locks in A-B then B-A order (the graph records ORDER, so a
    sequential single-threaded demonstration suffices — no timing
    race), and touch a loop-affine probe object from a short-lived
    foreign thread. Returns what was injected; the sanitizer findings
    are asserted by the chaos pipeline."""
    san = _SANITIZER
    if not san.enabled:
        return {"enabled": False}
    la = SanitizedLock(san, threading.Lock(), "chaos.inversion.a")
    lb = SanitizedLock(san, threading.Lock(), "chaos.inversion.b")
    with la:
        with lb:
            pass
    with lb:
        with la:
            pass
    san.tag("chaos.affinity_probe")
    t = threading.Thread(
        target=lambda: san.touch("chaos.affinity_probe"),
        name="chaos-foreign-toucher",
    )
    t.start()
    t.join(5.0)
    kinds = [f.kind for f in san.findings]
    return {
        "enabled": True,
        "injected": ["lock-order-cycle", "loop-affinity"],
        "observed": sorted(
            {
                k for k in kinds
                if k in ("lock-order-cycle", "loop-affinity")
            }
        ),
    }


def injected_finding(f: dict) -> bool:
    """True when a finding came from inject_lock_inversion's probes
    (chaos treats those as EXPECTED; everything else is a
    violation)."""
    detail = f.get("detail", {})
    names = list(detail.get("locks", [])) + [
        detail.get("object", "")
    ]
    return any(str(n).startswith("chaos.") for n in names)
