"""bftlint engine: walk paths, parse, run every rule, apply
suppressions.  Pure stdlib — importing this package must never pull
in jax (the linter runs in CI lanes with no accelerator deps)."""
from __future__ import annotations

import ast
import os
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from .findings import Finding
from .registry import FileContext, all_project_rules, all_rules
from .suppress import parse_suppressions

# repo root = parents[2] of this file (analysis/ -> cometbft_tpu/ -> .)
REPO_ROOT = Path(__file__).resolve().parents[2]

_SKIP_DIRS = {"__pycache__", ".git", ".hypothesis", "node_modules"}


def iter_py_files(paths: Iterable[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        path = Path(p)
        if not path.exists():
            # a typo'd path must be a hard error, not a "clean" run
            # (and never an accidental --update-baseline wipe)
            raise FileNotFoundError(f"no such path: {p}")
        if path.is_dir():
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(Path(dirpath) / fn)
        elif path.suffix == ".py":
            out.append(path)
    return out


def rel_key(path: Path, root: Path = REPO_ROOT) -> str:
    """Stable posix-style key for findings and baseline entries."""
    try:
        return path.resolve().relative_to(root).as_posix()
    except ValueError:
        return path.as_posix()


def _analyze_file(key: str, source: str, timings=None):
    """Parse + per-file rules for ONE source: the shared pipeline
    behind both analyze_source (tests) and run (the gate). Returns
    ``(findings, suppressions_or_None, tree_or_None)`` — tree is
    None when the file does not parse (SYN000 already appended)."""
    t0 = time.perf_counter()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return (
            [
                Finding(
                    key, e.lineno or 1, (e.offset or 1) - 1,
                    "SYN000", "syntax-error",
                    f"file does not parse: {e.msg}",
                )
            ],
            None,
            None,
        )
    finally:
        if timings is not None:
            timings["parse"] = (
                timings.get("parse", 0.0) + time.perf_counter() - t0
            )
    sup = parse_suppressions(key, source)
    ctx = FileContext(key, tree, source, source.splitlines())
    findings: List[Finding] = list(sup.errors)
    for r in all_rules():
        t0 = time.perf_counter()
        for f in r.check(ctx):
            if not sup.is_suppressed(f.line, f.rule_id):
                findings.append(f)
        if timings is not None:
            timings[r.rule_id] = (
                timings.get(r.rule_id, 0.0)
                + time.perf_counter() - t0
            )
    return findings, sup, tree


def analyze_source(source: str, path: str) -> List[Finding]:
    """Run every rule — file AND project (over a one-file project) —
    on one in-memory file (test entry point)."""
    findings, sup, tree = _analyze_file(path, source)
    if tree is not None:
        findings.extend(
            _run_project_rules([(path, tree)], {path: sup})
        )
    return sorted(findings)


def _run_project_rules(
    files, sups, timings: Optional[Dict[str, float]] = None
) -> List[Finding]:
    """Build the whole-program model once, then run every registered
    interprocedural rule over it (docs/LINT.md "Interprocedural
    rules"). Suppression comments apply exactly as for file rules,
    keyed by the finding's path."""
    from .callgraph import Project

    def sanctioned(path: str, line: int) -> bool:
        # a blocking-leaf line suppressed for ASY114 in ITS OWN file
        # is a sanctioned sink: chains through it vanish (see
        # callgraph.Project docstring / docs/LINT.md)
        sup = sups.get(path)
        return sup is not None and sup.is_suppressed(line, "ASY114")

    def suppressed(path: str, line: int, rule_id: str) -> bool:
        # generic per-line lookup for rules whose chains cross files
        # (ASY116 sanctions listener-registration lines by id)
        sup = sups.get(path)
        return sup is not None and sup.is_suppressed(line, rule_id)

    t0 = time.perf_counter()
    project = Project(
        list(files), sanctioned=sanctioned, suppressed=suppressed
    )
    if timings is not None:
        timings["callgraph-build"] = (
            timings.get("callgraph-build", 0.0)
            + time.perf_counter() - t0
        )
    out: List[Finding] = []
    for pr in all_project_rules():
        t0 = time.perf_counter()
        for f in pr.check(project):
            sup = sups.get(f.path)
            if sup is not None and sup.is_suppressed(f.line, f.rule_id):
                continue
            out.append(f)
        if timings is not None:
            key = f"{pr.rule_id}*"
            timings[key] = (
                timings.get(key, 0.0) + time.perf_counter() - t0
            )
    return out


def run(
    paths: Iterable[str],
    root: Path = REPO_ROOT,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Full pass: per-file rules over every file, then the
    interprocedural rules over the whole parsed set. ``timings``
    (optional dict) accumulates per-rule wall seconds — the CLI's
    ``--timings`` table, so the interprocedural pass's cost stays
    visible as the tree grows."""
    findings: List[Finding] = []
    parsed = []  # (key, tree) for the project pass
    sups = {}
    for file in iter_py_files(paths):
        key = rel_key(file, root)
        try:
            source = file.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(
                Finding(key, 1, 0, "SYN000", "syntax-error",
                        f"unreadable: {e}")
            )
            continue
        file_findings, sup, tree = _analyze_file(key, source, timings)
        findings.extend(file_findings)
        if tree is not None:
            sups[key] = sup
            parsed.append((key, tree))
    findings.extend(_run_project_rules(parsed, sups, timings))
    return sorted(findings)
