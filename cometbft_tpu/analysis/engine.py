"""bftlint engine: walk paths, parse, run every rule, apply
suppressions.  Pure stdlib — importing this package must never pull
in jax (the linter runs in CI lanes with no accelerator deps)."""
from __future__ import annotations

import ast
import os
from pathlib import Path
from typing import Iterable, List

from .findings import Finding
from .registry import FileContext, all_rules
from .suppress import parse_suppressions

# repo root = parents[2] of this file (analysis/ -> cometbft_tpu/ -> .)
REPO_ROOT = Path(__file__).resolve().parents[2]

_SKIP_DIRS = {"__pycache__", ".git", ".hypothesis", "node_modules"}


def iter_py_files(paths: Iterable[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        path = Path(p)
        if not path.exists():
            # a typo'd path must be a hard error, not a "clean" run
            # (and never an accidental --update-baseline wipe)
            raise FileNotFoundError(f"no such path: {p}")
        if path.is_dir():
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(Path(dirpath) / fn)
        elif path.suffix == ".py":
            out.append(path)
    return out


def rel_key(path: Path, root: Path = REPO_ROOT) -> str:
    """Stable posix-style key for findings and baseline entries."""
    try:
        return path.resolve().relative_to(root).as_posix()
    except ValueError:
        return path.as_posix()


def analyze_source(source: str, path: str) -> List[Finding]:
    """Run every rule over one in-memory file (test entry point)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [
            Finding(
                path, e.lineno or 1, (e.offset or 1) - 1,
                "SYN000", "syntax-error",
                f"file does not parse: {e.msg}",
            )
        ]
    sup = parse_suppressions(path, source)
    ctx = FileContext(path, tree, source, source.splitlines())
    findings: List[Finding] = list(sup.errors)
    for r in all_rules():
        for f in r.check(ctx):
            if not sup.is_suppressed(f.line, f.rule_id):
                findings.append(f)
    return sorted(findings)


def run(paths: Iterable[str], root: Path = REPO_ROOT) -> List[Finding]:
    findings: List[Finding] = []
    for file in iter_py_files(paths):
        key = rel_key(file, root)
        try:
            source = file.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(
                Finding(key, 1, 0, "SYN000", "syntax-error",
                        f"unreadable: {e}")
            )
            continue
        findings.extend(analyze_source(source, key))
    return sorted(findings)
