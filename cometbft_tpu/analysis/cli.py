"""bftlint CLI.

    python -m cometbft_tpu.analysis [paths...]

Exit codes: 0 clean (baselined violations allowed), 1 new violations
(or stale baseline under --fail-on-stale), 2 usage/internal error.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from . import baseline as baseline_mod
from .engine import REPO_ROOT, run
from .registry import all_project_rules, all_rules

DEFAULT_BASELINE = REPO_ROOT / "tools" / "bftlint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cometbft_tpu.analysis",
        description="bftlint: async-safety + JAX hot-path static "
        "analysis for cometbft_tpu",
    )
    p.add_argument(
        "paths", nargs="*", default=["cometbft_tpu"],
        help="files or directories to scan (default: cometbft_tpu)",
    )
    p.add_argument(
        "--baseline", default=str(DEFAULT_BASELINE),
        help="baseline file of pre-existing violations "
        f"(default: {DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report every violation, ignoring the baseline",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current violation set",
    )
    p.add_argument(
        "--fail-on-stale", action="store_true",
        help="exit 1 when baseline entries no longer match anything",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    p.add_argument(
        "--json", action="store_true",
        help="shorthand for --format json: machine-readable findings "
        "carrying rule, file, line, the interprocedural call chain "
        "and the domain-inference trace",
    )
    p.add_argument(
        "--changed-only", action="store_true",
        help="report findings only in files listed by `git diff "
        "--name-only HEAD` (staged + unstaged). The WHOLE project "
        "graph is still built — an interprocedural finding in a "
        "changed file can ride a chain through unchanged ones — "
        "only the report is scoped",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    p.add_argument(
        "--timings", action="store_true",
        help="print per-rule wall time (the interprocedural pass's "
        "cost must stay visible as the tree grows)",
    )
    return p


def _git_changed_files() -> set:
    """Repo-root-relative posix paths from ``git diff --name-only
    HEAD`` (staged + unstaged in one list) plus untracked .py files —
    the dev-loop scope for --changed-only."""
    import subprocess

    changed: set = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                cmd, cwd=str(REPO_ROOT), capture_output=True,
                text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if res.returncode == 0:
            changed.update(
                ln.strip() for ln in res.stdout.splitlines() if ln.strip()
            )
    return changed


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.json:
        args.format = "json"
    out = sys.stdout

    if args.list_rules:
        for r in all_rules():
            print(f"{r.rule_id}  {r.name}\n    {r.doc}", file=out)
        for pr in all_project_rules():
            print(
                f"{pr.rule_id}* {pr.name} (interprocedural)\n"
                f"    {pr.doc}",
                file=out,
            )
        return 0

    timings = {} if args.timings else None
    try:
        findings = run(args.paths, timings=timings)
    except FileNotFoundError as e:
        print(f"bftlint: {e}", file=sys.stderr)
        return 2
    if timings:
        total = sum(timings.values())
        print("bftlint rule timings (wall):", file=out)
        for name, secs in sorted(
            timings.items(), key=lambda kv: -kv[1]
        ):
            print(f"  {name:<18} {secs * 1e3:9.1f} ms", file=out)
        print(f"  {'total':<18} {total * 1e3:9.1f} ms", file=out)

    if args.update_baseline:
        entries = baseline_mod.build(findings)
        Path(args.baseline).parent.mkdir(parents=True, exist_ok=True)
        baseline_mod.save(args.baseline, entries)
        n = sum(sum(r.values()) for r in entries.values())
        print(
            f"bftlint: baseline written to {args.baseline} "
            f"({n} violations across {len(entries)} files)",
            file=out,
        )
        return 0

    stale: List[baseline_mod.StaleEntry] = []
    if not args.no_baseline:
        try:
            bl = (
                baseline_mod.load(args.baseline)
                if Path(args.baseline).exists()
                else {}
            )
        except (ValueError, OSError, json.JSONDecodeError) as e:
            print(f"bftlint: bad baseline: {e}", file=sys.stderr)
            return 2
        findings, stale = baseline_mod.apply(findings, bl)

    if args.changed_only:
        # scope the REPORT, not the analysis: the project graph above
        # covered everything, so chains through unchanged files still
        # resolved — this only drops findings outside the diff
        changed = _git_changed_files()
        findings = [f for f in findings if f.path in changed]
        stale = [s for s in stale if s.path in changed]

    if args.format == "json":
        json.dump(
            {
                "findings": [f.to_json() for f in findings],
                "stale_baseline": [s._asdict() for s in stale],
            },
            out, indent=1,
        )
        out.write("\n")
    else:
        for f in findings:
            print(f.render(), file=out)
        for s in stale:
            print(s.render(), file=out)
        if findings:
            print(
                f"bftlint: {len(findings)} new violation(s)", file=out
            )
        else:
            print("bftlint: clean", file=out)

    if findings:
        return 1
    if stale and args.fail_on_stale:
        return 1
    return 0
