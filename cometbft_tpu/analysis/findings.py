"""Finding: one rule violation at one source location."""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str  # posix-style, relative to the scan root when possible
    line: int  # 1-based
    col: int  # 0-based (ast convention)
    rule_id: str
    rule_name: str
    message: str
    # structured detail for machine consumers (--json): the call
    # chain an interprocedural finding rode in on, and the
    # domain-inference steps behind a complexity classification.
    # Defaults keep the positional 6-arg constructor (every existing
    # rule) and the frozen/order contract intact.
    chain: tuple = ()
    domain_trace: tuple = ()

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule_id} [{self.rule_name}] {self.message}"
        )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)
