"""Evidence types (reference types/evidence.go).

DuplicateVoteEvidence: two conflicting votes by one validator.
LightClientAttackEvidence: a conflicting light block + common height.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils import proto
from .. import types as T


@dataclass
class DuplicateVoteEvidence:
    vote_a: "T.Vote"
    vote_b: "T.Vote"
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp_ns: int = 0

    TYPE = 1

    @classmethod
    def from_votes(cls, a, b, val_power, total_power, time_ns):
        # canonical order: lexicographic by block id key (types/evidence.go)
        if a.block_id.key() > b.block_id.key():
            a, b = b, a
        return cls(a, b, total_power, val_power, time_ns)

    def height(self) -> int:
        return self.vote_a.height

    def addresses(self) -> List[bytes]:
        return [self.vote_a.validator_address]

    def encode(self) -> bytes:
        from ..utils import codec

        return (
            proto.field_varint(1, self.TYPE)
            + proto.field_message(2, codec.encode_vote(self.vote_a))
            + proto.field_message(3, codec.encode_vote(self.vote_b))
            + proto.field_varint(4, self.total_voting_power)
            + proto.field_varint(5, self.validator_power)
            + proto.field_message(6, proto.timestamp(self.timestamp_ns))
        )

    def hash(self) -> bytes:
        return hashlib.sha256(self.encode()).digest()

    def validate_basic(self) -> None:
        a, b = self.vote_a, self.vote_b
        if a is None or b is None:
            raise ValueError("missing vote")
        if a.block_id.key() >= b.block_id.key():
            raise ValueError("votes not in canonical order / identical")
        if (a.height, a.round, a.type_, a.validator_address) != (
            b.height,
            b.round,
            b.type_,
            b.validator_address,
        ):
            raise ValueError("votes do not conflict (different HRS/validator)")


@dataclass
class LightClientAttackEvidence:
    conflicting_block: object  # light.LightBlock
    common_height: int
    byzantine_validators: list = field(default_factory=list)
    total_voting_power: int = 0
    timestamp_ns: int = 0

    TYPE = 2

    def height(self) -> int:
        return self.common_height

    def encode(self) -> bytes:
        from ..utils import codec

        body = proto.field_varint(1, self.TYPE)
        lb = self.conflicting_block
        sh = proto.field_message(
            1, codec.encode_header(lb.header)
        ) + proto.field_message(2, codec.encode_commit(lb.commit))
        body += proto.field_message(2, sh)
        body += proto.field_message(
            3, codec.encode_validator_set(lb.validator_set)
        )
        body += proto.field_varint(4, self.common_height)
        body += proto.field_varint(5, self.total_voting_power)
        body += proto.field_message(6, proto.timestamp(self.timestamp_ns))
        from ..utils import codec

        body += b"".join(
            proto.field_message(7, codec.encode_validator(v))
            for v in self.byzantine_validators
        )
        return body

    def hash(self) -> bytes:
        return hashlib.sha256(self.encode()).digest()

    def validate_basic(self) -> None:
        if self.common_height < 1:
            raise ValueError("invalid common height")
        if self.conflicting_block is None:
            raise ValueError("missing conflicting block")

    def byzantine_from(self, common_vals) -> list:
        """The attack's byzantine set, derived (not trusted from the
        wire): signers of the conflicting commit that sit in the
        common validator set, descending power (reference
        types/evidence.go GetByzantineValidators — the lunatic-attack
        arm; both verifier and reporter compute THIS and the verifier
        rejects evidence whose claimed set differs)."""
        from ..types.block import BLOCK_ID_FLAG_COMMIT

        out = []
        for cs in self.conflicting_block.commit.signatures:
            if cs.block_id_flag != BLOCK_ID_FLAG_COMMIT:
                continue
            _, val = common_vals.get_by_address(cs.validator_address)
            if val is not None:
                out.append(val)
        out.sort(key=lambda v: (-v.voting_power, v.address))
        return out


def decode_evidence(b: bytes):
    from ..utils import codec
    from ..light.types import LightBlock

    m = proto.parse(b)
    t = proto.get1(m, 1, 0)
    if t == DuplicateVoteEvidence.TYPE:
        return DuplicateVoteEvidence(
            vote_a=codec.decode_vote(proto.get1(m, 2, b"")),
            vote_b=codec.decode_vote(proto.get1(m, 3, b"")),
            total_voting_power=proto.get1(m, 4, 0),
            validator_power=proto.get1(m, 5, 0),
            timestamp_ns=proto.parse_timestamp(proto.get1(m, 6, b"")),
        )
    if t == LightClientAttackEvidence.TYPE:
        shm = proto.parse(proto.get1(m, 2, b""))
        lb = LightBlock(
            header=codec.decode_header(proto.get1(shm, 1, b"")),
            commit=codec.decode_commit(proto.get1(shm, 2, b"")),
            validator_set=codec.decode_validator_set(proto.get1(m, 3, b"")),
        )
        return LightClientAttackEvidence(
            conflicting_block=lb,
            common_height=proto.get1(m, 4, 0),
            total_voting_power=proto.get1(m, 5, 0),
            timestamp_ns=proto.parse_timestamp(proto.get1(m, 6, b"")),
            byzantine_validators=[
                codec.decode_validator(x) for x in m.get(7, [])
            ],
        )
    raise ValueError(f"unknown evidence type {t}")
