"""Evidence reactor: gossip evidence on channel 0x38 (reference
evidence/reactor.go:17, broadcastEvidenceRoutine :107).

Every pending piece of evidence is periodically offered to every peer
(the pool dedups), and newly-added evidence is flooded immediately via
the pool's broadcast hook."""

from __future__ import annotations

import asyncio
import traceback
from typing import Dict

from ..p2p.node_info import ChannelDescriptor
from ..p2p.reactor import Reactor
from .types import decode_evidence

EVIDENCE_CHANNEL = 0x38
BROADCAST_INTERVAL_S = 0.5
MAX_PENDING_BYTES = 1 << 20


class EvidenceReactor(Reactor):
    name = "evidence"

    def __init__(self, evpool):
        super().__init__()
        self.evpool = evpool
        self._tasks: Dict[str, asyncio.Task] = {}

    def get_channels(self):
        return [
            ChannelDescriptor(EVIDENCE_CHANNEL, priority=6, max_msg_size=1 << 20)
        ]

    async def start(self) -> None:
        self.evpool.add_broadcast_hook(self._on_new_evidence)

    def _on_new_evidence(self, evd) -> None:
        if self.switch is not None:
            self.switch.broadcast(EVIDENCE_CHANNEL, evd.encode())

    def add_peer(self, peer) -> None:
        self._tasks[peer.peer_id] = asyncio.create_task(
            self._broadcast_routine(peer)
        )

    def remove_peer(self, peer, reason) -> None:
        t = self._tasks.pop(peer.peer_id, None)
        if t:
            t.cancel()

    async def stop(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()

    async def _broadcast_routine(self, peer) -> None:
        sent = set()
        try:
            while True:
                for evd in self.evpool.pending_evidence(MAX_PENDING_BYTES):
                    k = evd.hash()
                    if k in sent:
                        continue
                    await peer.send(EVIDENCE_CHANNEL, evd.encode())
                    sent.add(k)
                await asyncio.sleep(BROADCAST_INTERVAL_S)
        except asyncio.CancelledError:
            raise
        except Exception:
            traceback.print_exc()

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        try:
            evd = decode_evidence(msg)
        except Exception:
            self.switch.stop_peer_for_error(
                peer, ValueError("undecodable evidence")
            )
            return
        try:
            self.evpool.add_evidence(evd)
        except Exception:
            # invalid evidence from a peer is a protocol violation in
            # the reference (evidence/reactor.go Receive)
            pass
