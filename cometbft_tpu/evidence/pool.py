"""Evidence pool: detect/store/gossip misbehavior, feed the app for
slashing (reference evidence/pool.go).

Verification parity (evidence/verify.go): duplicate-vote evidence
checks both votes' signatures against the validator set at that height
(through the TPU batch path for the pair), height/age limits from
consensus params, and committed-evidence dedup.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from .. import types as T
from ..utils import kv
from .types import DuplicateVoteEvidence, LightClientAttackEvidence


class EvidenceError(Exception):
    pass


class EvidencePool:
    def __init__(self, db: kv.KV, state_store, block_store):
        self.db = db
        self.state_store = state_store
        self.block_store = block_store
        self._lock = threading.RLock()
        self._pending: dict = {}
        self._committed: set = set()
        self._broadcast_hooks: List = []

    def add_broadcast_hook(self, fn) -> None:
        self._broadcast_hooks.append(fn)

    # --- ingress ------------------------------------------------------

    def add_evidence(self, ev) -> None:
        with self._lock:
            key = ev.hash()
            if key in self._pending or key in self._committed:
                return
            self.verify(ev)
            self._pending[key] = ev
            self.db.set(b"EV:pend:" + key, ev.encode())
        for fn in self._broadcast_hooks:
            try:
                fn(ev)
            except Exception:
                pass

    def verify(self, ev) -> None:
        state = self.state_store.load()
        if state is None:
            raise EvidenceError("no state")
        params = state.consensus_params.evidence
        age_blocks = state.last_block_height - ev.height()
        if age_blocks > params.max_age_num_blocks:
            raise EvidenceError("evidence too old (blocks)")
        if isinstance(ev, DuplicateVoteEvidence):
            self._verify_duplicate_vote(ev, state)
        elif isinstance(ev, LightClientAttackEvidence):
            self._verify_lca(ev, state)
        else:
            raise EvidenceError("unknown evidence type")

    def _verify_duplicate_vote(self, ev: DuplicateVoteEvidence, state) -> None:
        ev.validate_basic()
        vals = self.state_store.load_validators(ev.height())
        if vals is None:
            if state.validators is None:
                raise EvidenceError("no validators for evidence height")
            vals = state.validators
        addr = ev.vote_a.validator_address
        idx, val = vals.get_by_address(addr)
        if val is None:
            raise EvidenceError("validator not found for evidence")
        chain_id = state.chain_id
        for v in (ev.vote_a, ev.vote_b):
            if not v.verify(chain_id, val.pub_key):
                raise EvidenceError("invalid signature on evidence vote")
        if ev.validator_power and ev.validator_power != val.voting_power:
            raise EvidenceError("evidence validator power mismatch")

    def _verify_lca(self, ev: LightClientAttackEvidence, state) -> None:
        ev.validate_basic()
        common_vals = self.state_store.load_validators(ev.common_height)
        if common_vals is None:
            raise EvidenceError("no validators at common height")
        lb = ev.conflicting_block
        # the conflicting block must be INTERNALLY consistent —
        # commit.block_id for the header, valset hashing to the
        # header's validators_hash (reference evidence ValidateBasic →
        # LightBlock.ValidateBasic, types/evidence.go:385). Without
        # this, a GENUINE commit (real signatures over the real block)
        # paired with a fabricated header would verify and slash the
        # honest signers.
        try:
            lb.validate_basic(state.chain_id)
        except ValueError as e:
            raise EvidenceError(
                f"invalid conflicting light block: {e}"
            )
        if ev.common_height > lb.height:
            raise EvidenceError(
                "common height is ahead of the conflicting block"
            )
        # the "conflicting" block must actually CONFLICT with our
        # chain: accepting evidence whose block matches our own header
        # would let anyone submit the real chain as an "attack" and
        # slash its honest signers (reference verify.go compares
        # against the locally trusted header). A height we cannot
        # compare (not yet synced) must REJECT, not skip — a lagging
        # node would otherwise accept the real chain's tip as
        # "evidence" (the reference errors when the trusted header is
        # unavailable); the reporter retries via gossip once we catch
        # up.
        ours = self.block_store.load_block_meta(lb.height)
        if ours is None:
            raise EvidenceError(
                f"cannot judge conflict at height {lb.height}: "
                "block not yet available locally"
            )
        if bytes(ours.block_id.hash) == bytes(lb.hash()):
            raise EvidenceError(
                "conflicting block matches our own chain (no attack)"
            )
        # trusting verification against the common valset, then full
        # verification by the conflicting block's own valset
        T.verify_commit_light_trusting(
            state.chain_id,
            common_vals,
            lb.commit,
            all_signatures=True,
            priority=T.PRIORITY_CATCHUP,
        )
        T.verify_commit_light(
            state.chain_id,
            lb.validator_set,
            lb.commit.block_id,
            lb.height,
            lb.commit,
            all_signatures=True,
            priority=T.PRIORITY_CATCHUP,
        )
        # the claimed byzantine set and total power must equal what WE
        # derive from the common valset — the slashing targets cannot
        # be attacker-chosen (reference evidence/verify.go:124-136)
        expected = ev.byzantine_from(common_vals)
        if [v.address for v in ev.byzantine_validators] != [
            v.address for v in expected
        ]:
            raise EvidenceError(
                "byzantine validators do not match the derived set"
            )
        for claimed, exp in zip(ev.byzantine_validators, expected):
            if claimed.voting_power != exp.voting_power:
                raise EvidenceError(
                    "byzantine validator power mismatch"
                )
        if ev.total_voting_power != common_vals.total_voting_power():
            raise EvidenceError(
                "evidence total voting power mismatch"
            )

    # --- egress -------------------------------------------------------

    def pending_evidence(self, max_bytes: int) -> List:
        with self._lock:
            out, total = [], 0
            for ev in self._pending.values():
                sz = len(ev.encode())
                if total + sz > max_bytes:
                    break
                out.append(ev)
                total += sz
            return out

    def check_evidence(self, evidence: List) -> None:
        """Validate a block's evidence list (reference CheckEvidence)."""
        seen = set()
        for ev in evidence:
            key = ev.hash()
            if key in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(key)
            with self._lock:
                if key in self._committed:
                    raise EvidenceError("evidence already committed")
                known = key in self._pending
            if not known:
                self.verify(ev)

    def update(self, state, block_evidence: List) -> None:
        with self._lock:
            for ev in block_evidence:
                key = ev.hash()
                self._committed.add(key)
                # value = the committing height: prune_below() can
                # age out markers without decoding evidence bodies
                self.db.set(
                    b"EV:comm:" + key,
                    state.last_block_height.to_bytes(8, "big"),
                )
                if key in self._pending:
                    del self._pending[key]
                    self.db.delete(b"EV:pend:" + key)
            # prune expired pending
            params = state.consensus_params.evidence
            for key, ev in list(self._pending.items()):
                if state.last_block_height - ev.height() > params.max_age_num_blocks:
                    del self._pending[key]
                    self.db.delete(b"EV:pend:" + key)

    def prune_below(self, height: int) -> int:
        """Retention-plane leg (store/retention.py): drop committed-
        evidence markers below ``height``, clamped so nothing inside
        the evidence max-age window ever goes — a marker still inside
        the window is what stops a committed duplicate from being
        re-proposed (check_evidence), so only markers that verify()
        would reject as expired anyway are prunable. One bounded
        batch; legacy b"\\x01" markers (no height) are kept."""
        state = self.state_store.load()
        if state is not None:
            max_age = state.consensus_params.evidence.max_age_num_blocks
            height = min(height, state.last_block_height - max_age)
        if height <= 0:
            return 0
        with self._lock:
            deletes = []
            for k, v in self.db.iter_prefix(b"EV:comm:"):
                h = int.from_bytes(v, "big") if len(v) == 8 else 0
                if h and h < height:
                    deletes.append(k)
            if deletes:
                self.db.write_batch([], deletes)
                for k in deletes:
                    self._committed.discard(k[len(b"EV:comm:"):])
        return len(deletes)

    def size(self) -> int:
        with self._lock:
            return len(self._pending)
