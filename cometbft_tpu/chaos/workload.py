"""Seeded workload plane for chaos runs (the scenario factory's
workload axis, docs/CHAOS.md "Scenario factory").

A ``WorkloadSpec`` declares the tx-storm shape; a ``WorkloadDriver``
pumps deterministic txs into the running net for the whole schedule,
riding the PR 5 ingest plane when present (``MempoolReactor.ingest``
micro-batches + sheds under overload) and falling back to direct
``mempool.check_tx``. Tx payloads are a pure function of (workload
seed, sequence number), so two same-seed runs submit byte-identical
tx streams — the workload is part of the replay contract exactly
like the link-fault decision streams.

Patterns:

- ``sustained`` — a steady ``tps`` trickle, the baseline load every
  scenario should survive;
- ``bursty`` — ``burst_txs`` back-to-back txs, then ``burst_gap_s``
  of silence: exercises ingest-queue backpressure + shed counters;
- ``none`` — no workload (pure fault schedules).

``tx_bytes`` pads every tx to a fixed size (large-tx storms stress
gossip framing + WAL record sizes). Specs round-trip through JSON so
a scenario file fully describes its run.
"""

from __future__ import annotations

import asyncio
import json
import random
from dataclasses import asdict, dataclass
from typing import Optional

PATTERNS = ("none", "sustained", "bursty")


@dataclass
class WorkloadSpec:
    pattern: str = "sustained"
    tps: float = 40.0  # sustained: target submissions/s
    burst_txs: int = 64  # bursty: txs per burst
    burst_gap_s: float = 0.5  # bursty: silence between bursts
    tx_bytes: int = 32  # min tx size (padded), caps at max_tx_bytes
    targets: int = 2  # submit through the first N running nodes

    def __post_init__(self):
        if self.pattern not in PATTERNS:
            raise ValueError(f"unknown workload pattern {self.pattern!r}")
        if self.tx_bytes < 16:
            raise ValueError("tx_bytes >= 16 (key=value framing)")

    def to_dict(self) -> dict:
        return asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadSpec":
        return cls(**d)


class WorkloadDriver:
    """Background task pumping the spec's tx stream into the net.

    ``start(net)`` spawns the loop; ``stop()`` is bounded by
    construction (one cancel, the loop never blocks on a slow node —
    submissions are fire-and-forget). Counters: ``submitted`` (txs
    handed to an ingest plane or mempool), ``shed`` (ingest queue
    full — backpressure did its job, the tx is dropped by design)."""

    def __init__(self, spec: WorkloadSpec, seed: int):
        self.spec = spec
        self.seed = seed
        self.rng = random.Random(f"workload|{seed}")
        self.submitted = 0
        self.shed = 0
        self._seq = 0
        self._task: Optional[asyncio.Task] = None

    # --- tx stream (pure function of seed + seq) ----------------------

    def _next_tx(self) -> bytes:
        i = self._seq
        self._seq += 1
        key = b"w%d-%08d" % (self.seed & 0xFFFF, i)
        pad = self.spec.tx_bytes - len(key) - 1
        val = bytes(
            self.rng.randrange(97, 123) for _ in range(max(1, pad))
        )
        return key + b"=" + val

    # --- submission ---------------------------------------------------

    def _submit_one(self, net) -> None:
        running = net.running_nodes()
        if not running:
            return
        tx = self._next_tx()
        _, node = running[self._seq % min(self.spec.targets, len(running))]
        ingest = getattr(
            getattr(node, "mempool_reactor", None), "ingest", None
        )
        if ingest is not None and ingest.running:
            if ingest.submit_nowait(tx, sender="workload"):
                self.submitted += 1
            else:
                self.shed += 1
            return
        try:
            node.parts.mempool.check_tx(tx)
            self.submitted += 1
        except Exception:
            self.shed += 1  # node died mid-submit: the storm goes on

    async def _run(self, net) -> None:
        spec = self.spec
        if spec.pattern == "none":
            return
        while True:
            if spec.pattern == "sustained":
                self._submit_one(net)
                await asyncio.sleep(1.0 / max(1.0, spec.tps))
            else:  # bursty
                for _ in range(spec.burst_txs):
                    self._submit_one(net)
                await asyncio.sleep(spec.burst_gap_s)

    # --- lifecycle ----------------------------------------------------

    def start(self, net) -> "WorkloadDriver":
        from ..utils.tasks import spawn

        if self.spec.pattern != "none" and self._task is None:
            self._task = spawn(self._run(net), name="chaos-workload")
        return self

    async def stop(self) -> None:
        t, self._task = self._task, None
        if t is not None:
            t.cancel()
            try:
                await asyncio.wait_for(t, 5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass

    def stats(self) -> dict:
        return {
            "pattern": self.spec.pattern,
            "submitted": self.submitted,
            "shed": self.shed,
        }
