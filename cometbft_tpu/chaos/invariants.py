"""BFT invariant checkers for chaos runs.

Three invariants, matching the protocol's formal claims (the
agreement/liveness properties formalized for this family in "A
Tendermint Light Client", arxiv 2010.07031):

- **Agreement** — no two honest nodes commit different block IDs at
  the same height, under any <1/3-fault schedule. Checked
  incrementally while the run progresses AND with a full re-scan at
  end-of-run (the re-scan also catches post-hoc store corruption the
  incremental pass already certified — which is exactly how the
  injected byzantine mutation is detected).
- **Liveness** — after the last heal/restart the network height
  advances within a bound.
- **WAL-replay consistency** — a crash/restart loses no committed
  block and changes no committed block ID: the restarted node's store
  must extend its pre-crash prefix byte-for-byte.

Violations carry enough context (heights, node monikers, hex block
IDs) that together with the run's seed + schedule the exact failure
replays.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class InvariantViolation(AssertionError):
    def __init__(self, invariant: str, detail: str):
        super().__init__(f"[{invariant}] {detail}")
        self.invariant = invariant
        self.detail = detail


class AgreementChecker:
    """Tracks the first-seen committed block ID per height across all
    (assumed-honest) nodes; any disagreement is a violation."""

    def __init__(self):
        self._seen: Dict[int, Tuple[bytes, str]] = {}  # h -> (hash, who)
        self._progress: Dict[str, int] = {}  # node name -> checked up to

    def _check_one(self, name: str, height: int, got: Optional[bytes]):
        if got is None:
            return
        prev = self._seen.get(height)
        if prev is None:
            self._seen[height] = (got, name)
        elif prev[0] != got:
            raise InvariantViolation(
                "agreement",
                f"height {height}: {name} committed {got.hex()[:16]} "
                f"but {prev[1]} committed {prev[0].hex()[:16]}",
            )

    def check(self, nodes) -> None:
        """Incremental pass: only heights committed since last call.
        ``nodes``: iterable of (name, node) with node.block_id_hash_at
        + node.height (chaos/net.py running nodes)."""
        for name, node in nodes:
            start = self._progress.get(name, 0) + 1
            top = node.height
            for h in range(start, top + 1):
                self._check_one(name, h, node.block_id_hash_at(h))
            self._progress[name] = max(
                self._progress.get(name, 0), top
            )

    def final_check(self, nodes) -> None:
        """Authoritative end-of-run pass: re-scan EVERY height from
        scratch so nothing certified earlier escapes re-inspection."""
        self._seen.clear()
        self._progress.clear()
        for name, node in nodes:
            for h in range(1, node.height + 1):
                self._check_one(name, h, node.block_id_hash_at(h))


class WALReplayChecker:
    """Crash/restart consistency: snapshot the committed chain before
    a crash, require the restarted node to extend it unchanged."""

    def __init__(self):
        self.checks = 0

    @staticmethod
    def pre_crash(node) -> Dict[int, bytes]:
        return {
            h: node.block_id_hash_at(h)
            for h in range(1, node.height + 1)
        }

    def post_restart(self, name: str, node, snapshot: Dict[int, bytes]):
        self.checks += 1
        if snapshot and node.height < max(snapshot):
            raise InvariantViolation(
                "wal-replay",
                f"{name} lost committed blocks in crash/restart: "
                f"height {node.height} < pre-crash {max(snapshot)}",
            )
        for h, want in snapshot.items():
            got = node.block_id_hash_at(h)
            if got != want:
                raise InvariantViolation(
                    "wal-replay",
                    f"{name} height {h} changed across restart: "
                    f"{None if got is None else got.hex()[:16]} != "
                    f"{want.hex()[:16]}",
                )


def liveness_violation(
    heights: Dict[str, int], target: int, bound_s: float
) -> InvariantViolation:
    lag = {n: h for n, h in heights.items() if h < target}
    return InvariantViolation(
        "liveness",
        f"height {target} not reached within {bound_s:.0f}s after the "
        f"last heal: lagging {lag}",
    )
