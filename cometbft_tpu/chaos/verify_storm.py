"""Unified-verify-scheduler storm (nemesis action ``verify_storm``).

Drives a light-session storm AND a blocksync-style catch-up storm
concurrently with the net's own live consensus — all three through
the ONE process-wide VerifyScheduler (crypto/scheduler.py, chaos
nodes are in-process so they share the singleton). The assertions
are the scheduler's contract under contention:

- **verdict parity**: every ticket's merged verdicts must equal the
  per-key host math, bad signatures included — a parity miss under
  concurrency is a merge/ordering bug the quiet tests can't see;
- **priority-class latency**: the synthetic LIVE tickets' p95
  submit→resolve wall must hold the ``crypto.sched.dispatch`` class
  budget while the storms saturate the engine — chunk-granularity
  preemption is what bounds it;
- **no starvation**: the catch-up feeder must keep completing
  tickets for the storm's whole duration (aging promotion), not
  stall behind the live/light load.

Runs in a worker thread (``asyncio.to_thread`` from the nemesis —
pure CPU + blocking waits would trip the loop-stall detector the
matrix itself polices). Timing values in the record are measured,
not seeded; the verdict assertions are deterministic.
"""

from __future__ import annotations

import threading
import time
from typing import List

from ..crypto import scheduler as sched_mod
from ..crypto.keys import Ed25519PrivKey
from ..utils.log import get_logger
from .invariants import InvariantViolation

_log = get_logger("chaos.verify_storm")


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(q * (len(vs) - 1) + 0.5))]


def _make_pool(n: int, bad: frozenset, keys) -> tuple:
    items = []
    for i in range(n):
        sk = keys[i % len(keys)]
        msg = b"verify-storm-%d" % i
        sig = sk.sign(msg)
        if i in bad:
            sig = b"\x00" * 64
        items.append((sk.pub_key(), msg, sig))
    expected = [i not in bad for i in range(n)]
    return items, expected


def storm_for_chaos(
    storm_s: float = 1.5, live_budget_ms: float = 2500.0
) -> dict:
    """Run the three-class storm; returns the nemesis trace record.
    Raises InvariantViolation on parity loss, a live-class budget
    breach, or a starved catch-up lane."""
    s = sched_mod.scheduler()
    keys = [Ed25519PrivKey.generate() for _ in range(4)]
    live_items, live_want = _make_pool(8, frozenset(), keys)
    light_items, light_want = _make_pool(16, frozenset({3}), keys)
    catchup_items, catchup_want = _make_pool(64, frozenset({11, 40}), keys)
    promoted_before = s.promoted
    deadline = time.perf_counter() + storm_s
    walls = {0: [], 1: [], 2: []}
    parity_misses: List[str] = []
    lock = threading.Lock()

    def run_class(priority, items, want, label, pause_s):
        while time.perf_counter() < deadline:
            t = s.submit(items, priority=priority, label=label)
            try:
                _, oks = t.result(timeout=30.0)
            except TimeoutError:
                with lock:
                    parity_misses.append(f"{label}: ticket timed out")
                return
            with lock:
                if oks != want:
                    parity_misses.append(
                        f"{label}: verdicts diverged under storm"
                    )
                walls[priority].append(t.wall() or 0.0)
            if pause_s:
                time.sleep(pause_s)

    feeders = [
        threading.Thread(
            target=run_class,
            args=(sched_mod.PRIORITY_LIGHT, light_items, light_want,
                  "storm-light", 0.005),
            daemon=True,
        ),
        threading.Thread(
            target=run_class,
            args=(sched_mod.PRIORITY_CATCHUP, catchup_items,
                  catchup_want, "storm-catchup", 0.0),
            daemon=True,
        ),
    ]
    for f in feeders:
        f.start()
    # the LIVE lane runs on the calling worker thread: small frequent
    # waves, the shape of a precommit burst
    run_class(
        sched_mod.PRIORITY_LIVE, live_items, live_want,
        "storm-live", 0.02,
    )
    for f in feeders:
        f.join(timeout=60.0)
    s.drain(timeout=60.0)

    record = {"storm_s": storm_s, "live_budget_ms": live_budget_ms}
    for cls, name in enumerate(sched_mod.CLASS_NAMES):
        w = walls[cls]
        record[name] = {
            "tickets": len(w),
            "p50_ms": round(_percentile(w, 0.50) * 1000.0, 3),
            "p95_ms": round(_percentile(w, 0.95) * 1000.0, 3),
        }
    record["promoted"] = s.promoted - promoted_before
    record["parity_ok"] = not parity_misses

    if parity_misses:
        raise InvariantViolation(
            "verify_parity",
            f"scheduler verdicts diverged under storm: "
            f"{parity_misses[:3]}",
        )
    live_p95_ms = record["live"]["p95_ms"]
    if record["live"]["tickets"] and live_p95_ms > live_budget_ms:
        raise InvariantViolation(
            "verify_priority",
            f"live-class verify p95 {live_p95_ms:.0f}ms breached the "
            f"{live_budget_ms:.0f}ms budget while sharing the "
            "scheduler with light+catch-up storms",
        )
    if not record["catchup"]["tickets"]:
        raise InvariantViolation(
            "verify_starvation",
            "catch-up lane completed ZERO tickets during the storm: "
            "aging promotion failed to hold its dispatch share",
        )
    _log.info("verify storm complete", **{
        k: v for k, v in record.items() if not isinstance(v, dict)
    })
    return record
