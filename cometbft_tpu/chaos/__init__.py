"""Deterministic chaos harness: seeded network fault plane + nemesis
scheduler + BFT invariant checkers.

Quick start (see docs/CHAOS.md for the full story)::

    from cometbft_tpu.chaos import default_schedule, run_schedule
    report = await run_schedule(default_schedule(), seed=1337,
                                base_dir=tmpdir)
    assert report.ok, report.format()

CLI: ``python -m cometbft_tpu.chaos --seed 1337`` (tools/chaos_smoke.sh).
"""

from .invariants import (
    AgreementChecker,
    InvariantViolation,
    WALReplayChecker,
)
from .links import ChaosConnection, LinkState, LinkTable
from .nemesis import Nemesis
from .net import ChaosNet, ChaosReport, run_schedule
from .schedule import FaultEvent, FaultSchedule, default_schedule

__all__ = [
    "AgreementChecker",
    "ChaosConnection",
    "ChaosNet",
    "ChaosReport",
    "FaultEvent",
    "FaultSchedule",
    "InvariantViolation",
    "LinkState",
    "LinkTable",
    "Nemesis",
    "WALReplayChecker",
    "default_schedule",
    "run_schedule",
]
