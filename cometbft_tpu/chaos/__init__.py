"""Deterministic chaos harness: seeded network fault plane + nemesis
scheduler + BFT invariant checkers.

Quick start (see docs/CHAOS.md for the full story)::

    from cometbft_tpu.chaos import default_schedule, run_schedule
    report = await run_schedule(default_schedule(), seed=1337,
                                base_dir=tmpdir)
    assert report.ok, report.format()

CLI: ``python -m cometbft_tpu.chaos --seed 1337`` (tools/chaos_smoke.sh);
scenario factory: ``python -m cometbft_tpu.chaos matrix --seed 1337
--count 5`` (docs/CHAOS.md "Scenario factory").
"""

from .generator import (
    LIFECYCLES,
    ScenarioSpec,
    generate_matrix,
    generate_scenario,
)
from .invariants import (
    AgreementChecker,
    InvariantViolation,
    WALReplayChecker,
)
from .links import ChaosConnection, LinkState, LinkTable
from .matrix import MatrixReport, run_matrix, run_scenario
from .nemesis import Nemesis
from .net import ChaosNet, ChaosReport, run_schedule
from .schedule import FaultEvent, FaultSchedule, default_schedule
from .workload import WorkloadDriver, WorkloadSpec

__all__ = [
    "AgreementChecker",
    "ChaosConnection",
    "ChaosNet",
    "ChaosReport",
    "FaultEvent",
    "FaultSchedule",
    "InvariantViolation",
    "LIFECYCLES",
    "LinkState",
    "LinkTable",
    "MatrixReport",
    "Nemesis",
    "ScenarioSpec",
    "WALReplayChecker",
    "WorkloadDriver",
    "WorkloadSpec",
    "default_schedule",
    "generate_matrix",
    "generate_scenario",
    "run_matrix",
    "run_scenario",
    "run_schedule",
]
