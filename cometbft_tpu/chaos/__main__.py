"""CLI: one seeded chaos run, or the scenario-factory matrix.

    python -m cometbft_tpu.chaos --seed 1337 [--nodes 4]
        [--schedule sched.json] [--byzantine N] [--json out.json]
    python -m cometbft_tpu.chaos matrix --seed 1337 --count 5
    python -m cometbft_tpu.chaos soak --heights 10000 --step 50

Exit code 0 when every invariant holds, 1 on any violation (the
report — seed, fault trace, per-link decisions — prints either way),
2 on a span-budget breach only. With --byzantine the run is EXPECTED
to be flagged: exit codes invert so CI can assert the checker
actually fires. The ``matrix`` subcommand generates + runs seeded
workload x network x lifecycle scenarios (chaos/generator.py,
docs/CHAOS.md "Scenario factory").
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import tempfile

from .net import run_schedule
from .schedule import FaultSchedule, default_schedule


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "matrix":
        from .matrix import matrix_main

        return matrix_main(argv[1:])
    if argv and argv[0] == "soak":
        from .soak import soak_main

        return soak_main(argv[1:])
    ap = argparse.ArgumentParser(prog="python -m cometbft_tpu.chaos")
    ap.add_argument("--seed", type=int, default=1337)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--schedule", help="fault schedule JSON file")
    ap.add_argument(
        "--byzantine",
        type=int,
        default=None,
        metavar="N",
        help="inject a commit corruption at node N (detection check: "
        "exit 0 iff the agreement checker FLAGS the run)",
    )
    ap.add_argument("--liveness-bound", type=float, default=60.0)
    ap.add_argument("--json", help="write the report as JSON here")
    ap.add_argument(
        "--budget",
        nargs="?",
        const="",
        default=None,
        metavar="FILE",
        help="evaluate span budgets over the run's trace rings "
        "(default file tools/span_budgets.toml); any breach dumps "
        "traces and exits 2",
    )
    ap.add_argument(
        "--expect-stall",
        action="store_true",
        help="flight-recorder check: exit 0 iff a loop stall was "
        "captured whose snapshot contains the injected chaos_stall "
        "frame (pair with a schedule carrying a stall event)",
    )
    ap.add_argument(
        "--expect-lock-inversion",
        action="store_true",
        help="sanitizer check: exit 0 iff the runtime concurrency "
        "sanitizer reported the scheduled lock_inversion's ABBA "
        "cycle AND the foreign-thread affinity touch (pair with a "
        "schedule carrying a lock_inversion event)",
    )
    ap.add_argument(
        "--expect-scaling-violation",
        action="store_true",
        help="scaling-probe check: exit 0 iff the committee-scaling "
        "probe flagged the planted quadratic site over its exponent "
        "budget (pair with a schedule carrying a scaling_probe "
        "event with inject_quadratic)",
    )
    ap.add_argument(
        "--trace-dump",
        metavar="DIR",
        help="export every node's trace ring here (JSONL per node + "
        "Perfetto trace.json); without it violated runs still dump "
        "to a fresh temp directory",
    )
    ap.add_argument(
        "--light-storm",
        type=int,
        default=0,
        metavar="N",
        help="after the fault schedule settles, drive N light-client "
        "serving sessions against a live node through the shared "
        "serving plane (light/serving.py) — served blocks are "
        "hash-asserted against the node's store and the "
        "light.serve.request spans land in its ring (budget-gated "
        "with --budget)",
    )
    ap.add_argument(
        "--subscriber-storm",
        type=int,
        default=0,
        metavar="N",
        help="after the fault schedule settles, open N websocket "
        "subscribers against a live node's RPC and require every one "
        "to receive consecutive NewBlock events store-verified on "
        "the node — zero sheds, one serialization per event "
        "(rpc/fanout.py; budget-gated with --budget via the "
        "fanout.deliver span)",
    )
    ap.add_argument(
        "--fleet",
        type=int,
        default=0,
        metavar="N",
        help="attach an in-process serving fleet of N follower "
        "replicas behind a SessionRouter for the whole run "
        "(cometbft_tpu/fleet, docs/FLEET.md): routed subscriber "
        "sessions stream commits throughout, a scheduled "
        "replica_kill strands them mid-stream, and the run asserts "
        "lossless failover (zero lost commits) + lag-shed isolation "
        "(a replica_kill in the schedule implies --fleet 3)",
    )
    ap.add_argument(
        "--fastpath",
        action="store_true",
        help="run every node with the live-consensus fast path "
        "(WAL group commit + vote micro-batching + pipelined "
        "finalize, docs/PERF.md) under a 2ms slow-disk fsync model",
    )
    args = ap.parse_args(argv)

    if args.schedule:
        with open(args.schedule) as f:
            schedule = FaultSchedule.from_json(f.read())
    else:
        schedule = default_schedule(byzantine_node=args.byzantine)

    budget_file = None
    if args.budget is not None:
        from ..obs.budget import default_budget_file

        budget_file = args.budget or default_budget_file()

    config_hook = None
    if args.fastpath:
        from ..consensus import wal as walmod
        from .matrix import fastpath_config_hook

        config_hook = fastpath_config_hook
        walmod.set_fsync_model(0.002)
    try:
        with tempfile.TemporaryDirectory(prefix="chaos_") as tmp:
            report = asyncio.run(
                run_schedule(
                    schedule,
                    seed=args.seed,
                    base_dir=tmp,
                    n_nodes=args.nodes,
                    liveness_bound_s=args.liveness_bound,
                    trace_dir=args.trace_dump,
                    budget_file=budget_file,
                    config_hook=config_hook,
                    light_storm=args.light_storm,
                    subscriber_storm=args.subscriber_storm,
                    fleet=args.fleet,
                )
            )
    finally:
        if args.fastpath:
            from ..consensus import wal as walmod

            walmod.set_fsync_model(0.0)
    print(report.format())
    if args.json:
        with open(args.json, "w") as f:
            json.dump(
                {
                    "seed": report.seed,
                    "ok": report.ok,
                    "violations": report.violations,
                    "trace": report.trace,
                    "final_heights": report.final_heights,
                    "link_decisions": report.link_decisions,
                    "wal_checks": report.wal_checks,
                    "trace_files": report.trace_files,
                    "schedule": json.loads(report.schedule_json),
                    "stall_records": report.stall_records,
                    "budget_verdicts": report.budget_verdicts,
                    "profile_file": report.profile_file,
                    "workload": report.workload,
                    "shutdown_stalls": report.shutdown_stalls,
                    "proposers": report.proposers,
                    "light_storm": report.light_storm,
                    "subscriber_storm": report.subscriber_storm,
                    "fleet": report.fleet,
                    "sanitizer_findings": report.sanitizer_findings,
                },
                f,
                indent=2,
            )
    if args.expect_stall:
        caught = any(
            any("chaos_stall" in ln for ln in r.get("loop_stack", []))
            for r in report.stall_records
        )
        print(
            "stall flight-record:",
            "CAPTURED (chaos_stall frame in snapshot)"
            if caught
            else "MISSED",
        )
        if not caught:
            return 1
    if args.expect_lock_inversion:
        from ..analysis.runtime import injected_finding

        # only the INJECTED findings count as detection (a real,
        # un-injected cycle elsewhere must not mask a missed
        # injection — same filter run_schedule applies)
        kinds = {
            f.get("kind")
            for f in report.sanitizer_findings
            if injected_finding(f)
        }
        caught = {"lock-order-cycle", "loop-affinity"} <= kinds
        print(
            "sanitizer lock-inversion:",
            "DETECTED (ABBA cycle + foreign-thread touch reported)"
            if caught
            else f"MISSED (got {sorted(kinds)})",
        )
        if not caught:
            return 1
    if args.expect_scaling_violation:
        # only the INJECTED site counts as detection (same filter as
        # the sanitizer check: a real breach elsewhere must not mask
        # a probe that missed its own plant)
        hits = [
            r
            for r in report.scaling_results
            if r.get("injected") and not r.get("ok")
        ]
        print(
            "scaling-probe quadratic plant:",
            f"DETECTED (exponent {hits[0].get('exponent')} over "
            f"budget {hits[0].get('budget')})"
            if hits
            else "MISSED",
        )
        if not hits:
            return 1
    if args.byzantine is not None:
        detected = any("agreement" in v for v in report.violations)
        print(
            "byzantine detection:",
            "DETECTED" if detected else "MISSED",
        )
        return 0 if detected else 1
    if not report.ok:
        return 1
    if not report.budget_ok:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
