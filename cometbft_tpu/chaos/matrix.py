"""Matrix runner: execute generated scenarios, gate on invariants +
span budgets, print the replay seed line per scenario.

    python -m cometbft_tpu.chaos matrix --seed 1337 --count 5

Exit codes: 0 all scenarios invariant- and budget-clean, 1 any
invariant violation, 2 budget breaches only. Every scenario prints
its seed line FIRST, so a wedged/violated run's replay handle is
already on screen; ``--only I`` replays exactly scenario I.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils.log import get_logger
from .generator import ScenarioSpec, generate_matrix
from .net import ChaosReport, run_schedule

_log = get_logger("chaos.matrix")


@dataclass
class ScenarioResult:
    spec: ScenarioSpec
    report: Optional[ChaosReport] = None
    error: str = ""

    @property
    def ok(self) -> bool:
        return not self.error and self.report is not None and self.report.ok

    @property
    def budget_ok(self) -> bool:
        return self.report is None or self.report.budget_ok


@dataclass
class MatrixReport:
    master_seed: int
    results: List[ScenarioResult] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    @property
    def budget_ok(self) -> bool:
        return all(r.budget_ok for r in self.results)

    @property
    def exit_code(self) -> int:
        if not self.ok:
            return 1
        if not self.budget_ok:
            return 2
        return 0

    def format_table(self) -> str:
        head = (
            f"{'scenario':<12} {'axes':<44} {'nodes':>5} "
            f"{'heights':<24} {'invariants':<11} {'budgets':<8}"
        )
        lines = [head, "-" * len(head)]
        for r in self.results:
            ax = ",".join(
                r.spec.axes[k]
                for k in ("workload", "network", "lifecycle")
            )
            if r.error:
                verdict, budget = "ERROR", "-"
                heights = r.error[:24]
            else:
                verdict = (
                    "OK" if r.report.ok
                    else f"{len(r.report.violations)} VIOLATED"
                )
                budget = "OK" if r.report.budget_ok else "BREACH"
                heights = ",".join(
                    str(h)
                    for h in r.report.final_heights.values()
                )[:24]
            lines.append(
                f"{r.spec.scenario_id:<12} {ax:<44} "
                f"{r.spec.n_nodes:>5} {heights:<24} {verdict:<11} "
                f"{budget:<8}"
            )
        return "\n".join(lines)


def fastpath_config_hook(cfg) -> None:
    """Enable the live-consensus fast path (docs/PERF.md) on every
    node of a chaos run: WAL group commit + in-round vote
    micro-batching + pipelined finalize. Used by ``matrix
    --fastpath`` so the fault matrix proves the fast path clean, not
    just fast."""
    cfg.consensus.wal_group_commit_ms = 2.0
    cfg.consensus.vote_batch_window_ms = 2.0
    cfg.consensus.finalize_pipeline = True


async def run_scenario(
    spec: ScenarioSpec,
    base_dir: str,
    budget_file: Optional[str] = None,
    trace_dir: Optional[str] = None,
    config_hook=None,
) -> ChaosReport:
    """One generated scenario through the standard chaos entrypoint
    (the same path hand-written schedules use — generated scenarios
    get no special treatment from the invariant checkers)."""
    return await run_schedule(
        spec.schedule,
        seed=spec.seed,
        base_dir=base_dir,
        n_nodes=spec.n_nodes,
        settle_heights=spec.settle_heights,
        liveness_bound_s=spec.liveness_bound_s,
        trace_dir=trace_dir,
        budget_file=budget_file,
        workload=spec.workload,
        config_hook=config_hook,
    )


async def run_matrix(
    specs: List[ScenarioSpec],
    budget_file: Optional[str] = None,
    trace_dir: Optional[str] = None,
    out_dir: Optional[str] = None,
    config_hook=None,
) -> MatrixReport:
    master = specs[0].master_seed if specs else 0
    matrix = MatrixReport(master_seed=master)
    for spec in specs:
        print(spec.seed_line(), flush=True)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            with open(
                os.path.join(
                    out_dir, f"{spec.scenario_id}.scenario.json"
                ),
                "w",
            ) as f:
                f.write(spec.to_json())
        res = ScenarioResult(spec=spec)
        matrix.results.append(res)
        sub_trace = (
            os.path.join(trace_dir, spec.scenario_id)
            if trace_dir
            else None
        )
        with tempfile.TemporaryDirectory(
            prefix=f"chaos_{spec.scenario_id}_"
        ) as tmp:
            try:
                res.report = await run_scenario(
                    spec,
                    base_dir=tmp,
                    budget_file=budget_file,
                    trace_dir=sub_trace,
                    config_hook=config_hook,
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # scenario crash != run violation
                res.error = repr(e)
                _log.error(
                    "scenario errored",
                    scenario=spec.scenario_id,
                    err=repr(e),
                )
                continue
        verdict = (
            "OK"
            if res.report.ok and res.report.budget_ok
            else "VIOLATED"
            if not res.report.ok
            else "BUDGET BREACH"
        )
        print(
            f"  -> {verdict} heights={res.report.final_heights} "
            f"workload={res.report.workload or 'none'}",
            flush=True,
        )
    return matrix


def matrix_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cometbft_tpu.chaos matrix",
        description=(
            "Seeded scenario matrix: generate + run workload x "
            "network x lifecycle chaos scenarios (docs/CHAOS.md "
            '"Scenario factory")'
        ),
        epilog=(
            "examples:\n"
            "  chaos matrix --seed 1337 --count 5        "
            "# the 5-scenario smoke (covers statesync_join, "
            "crash_wave, wal_torn_tail)\n"
            "  chaos matrix --seed 1337 --only 3         "
            "# replay scenario 3 byte-for-byte\n"
            "  chaos matrix --seed 7 --count 50 --profile soak  "
            "# nightly-sized soak\n"
            "  chaos matrix --seed 1337 --count 5 --list "
            "# print scenarios without running"
        ),
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--seed", type=int, default=1337,
                    help="master seed (scenario i is a pure function "
                    "of (seed, i))")
    ap.add_argument("--count", type=int, default=5)
    ap.add_argument(
        "--only", type=int, action="append", default=None,
        metavar="I",
        help="run only scenario index I (repeatable) — the replay "
        "handle printed in every seed line",
    )
    ap.add_argument("--nodes", type=int, default=None,
                    help="override the generated committee size")
    ap.add_argument(
        "--profile", choices=("smoke", "soak"), default="smoke",
        help="soak allows larger committees (5/7 nodes)",
    )
    ap.add_argument(
        "--budget",
        nargs="?",
        const="",
        default=None,
        metavar="FILE",
        help="evaluate span budgets per scenario (default file "
        "tools/span_budgets.toml); any breach exits 2",
    )
    ap.add_argument("--out", metavar="DIR",
                    help="write each scenario's JSON spec here")
    ap.add_argument("--trace-dump", metavar="DIR",
                    help="export every scenario's trace rings under "
                    "DIR/<scenario_id>/")
    ap.add_argument("--json", help="write the matrix report here")
    ap.add_argument(
        "--fastpath", action="store_true",
        help="run every node with the live-consensus fast path on "
        "(WAL group commit + vote micro-batching + pipelined "
        "finalize, docs/PERF.md) under a 2ms slow-disk fsync model "
        "so the group seam genuinely engages — proves the fast path "
        "fault-clean, not just fast",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="print the generated scenarios (seed lines + schedule "
        "JSON) without running them",
    )
    args = ap.parse_args(argv)

    specs = generate_matrix(
        args.seed,
        args.count,
        n_nodes=args.nodes,
        profile=args.profile,
        only=args.only,
    )
    if args.list:
        for spec in specs:
            print(spec.seed_line())
            print(spec.to_json())
        return 0

    budget_file = None
    if args.budget is not None:
        from ..obs.budget import default_budget_file

        budget_file = args.budget or default_budget_file()

    config_hook = None
    if args.fastpath:
        from ..consensus import wal as walmod

        config_hook = fastpath_config_hook
        # the calibrated WAL router keeps the strict path on this
        # box's ~0.1ms fsyncs; the model makes barriers sync-through-
        # disk-expensive so crashes/torn tails land INSIDE group
        # windows (restored below)
        walmod.set_fsync_model(0.002)
    try:
        matrix = asyncio.run(
            run_matrix(
                specs,
                budget_file=budget_file,
                trace_dir=args.trace_dump,
                out_dir=args.out,
                config_hook=config_hook,
            )
        )
    finally:
        if args.fastpath:
            walmod.set_fsync_model(0.0)
    print()
    print(matrix.format_table())
    for r in matrix.results:
        if r.report is not None and not r.report.ok:
            print()
            print(r.spec.seed_line())
            print(r.report.format())
    if args.json:
        with open(args.json, "w") as f:
            json.dump(
                {
                    "master_seed": matrix.master_seed,
                    "ok": matrix.ok,
                    "budget_ok": matrix.budget_ok,
                    "scenarios": [
                        {
                            "spec": r.spec.to_dict(),
                            "error": r.error,
                            "ok": r.ok,
                            "budget_ok": r.budget_ok,
                            "violations": (
                                r.report.violations
                                if r.report
                                else []
                            ),
                            "final_heights": (
                                r.report.final_heights
                                if r.report
                                else {}
                            ),
                            "workload": (
                                r.report.workload if r.report else {}
                            ),
                            "proposers": (
                                r.report.proposers if r.report else {}
                            ),
                            "trace": (
                                r.report.trace if r.report else []
                            ),
                        }
                        for r in matrix.results
                    ],
                },
                f,
                indent=2,
            )
    return matrix.exit_code
